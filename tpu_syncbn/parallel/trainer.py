"""Data-parallel trainer — the TPU-native replacement for
``DistributedDataParallel`` (reference ``README.md:62-72``; implementation
``[torch] nn/parallel/distributed.py:466-2666``).

DDP's machinery maps onto the compiled step as follows (SURVEY §7):

=====================================================  ======================
DDP mechanism                                          here
=====================================================  ======================
init-time param/buffer broadcast from rank 0           :func:`sync_module_states`
(``_sync_module_states``, ``distributed.py:1066``)     (+ identical-by-
                                                       construction init)
autograd-hook bucketing + overlapped all_reduce        ``lax.pmean`` of grads
(C++ Reducer, ``distributed.py:1437``; 25 MiB           inside the jitted
buckets ``:31``)                                       step; XLA's latency-
                                                       hiding scheduler
                                                       overlaps it with the
                                                       backward automatically
gradient averaging by world size                       ``pmean`` (sum/world)
per-forward buffer broadcast                           rank-0 buffer
(``forward_sync_buffers``, ``:793``)                   broadcast of BatchStats
                                                       inside the step
``no_sync()`` gradient accumulation (``:1659``)        ``accum_steps`` —
                                                       lax.scan microbatches,
                                                       one pmean at the end
``find_unused_parameters`` (``:719``)                  unnecessary: autodiff
                                                       yields zero grads for
                                                       unused params, every
                                                       replica identically
=====================================================  ======================

The key structural difference: DDP is a runtime wrapper issuing collectives
from autograd hooks; here the *compiler* sees the whole step (forward,
backward, stat sync, grad sync, optimizer) as one XLA program and schedules
the collectives over ICI itself — which is what subsumes bucketing/overlap
tuning.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import nnx
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_syncbn import compat
from tpu_syncbn.compat import shard_map
from tpu_syncbn.obs import numerics as obs_numerics, stepstats as obs_stepstats
from tpu_syncbn.parallel import collectives
from tpu_syncbn.parallel.collectives import pcast_varying as _pcast_varying
from tpu_syncbn.runtime import distributed as dist
from tpu_syncbn.runtime.distributed import DATA_AXIS


def sync_module_states(model: nnx.Module, src: int = 0) -> None:
    """Broadcast parameters and buffers from host ``src`` to all hosts —
    DDP's init-time ``_sync_module_states``
    (``[torch] nn/parallel/distributed.py:1066-1072``).

    In single-program SPMD, replicas created from the same PRNG key are
    identical by construction, so this matters only for multi-host jobs
    where hosts may have diverged (e.g. loaded different checkpoints).
    Single-host: no-op.
    """
    if dist.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    graphdef, state = nnx.split(model)
    state = multihost_utils.broadcast_one_to_all(
        state, is_source=dist.process_index() == src
    )
    nnx.update(model, state)


def _model_traces_pallas_bn(model: nnx.Module) -> bool:
    """Will compiling a step over ``model`` actually trace the Pallas BN
    kernels? True only when the global mode selects Pallas AND the model
    contains a channel-last, ungrouped BatchNorm (the fast-path gate in
    ops/batch_norm.py) — so e.g. group-scoped or channel-first models
    keep the VMA checker even on TPU."""
    from tpu_syncbn.nn.normalization import BatchNorm
    from tpu_syncbn.ops import batch_norm as bn_ops

    if not bn_ops._use_pallas():
        return False
    for _, node in nnx.iter_graph(model):
        if (
            isinstance(node, BatchNorm)
            and node.channel_axis == -1
            and node.group_size is None
            and getattr(node, "stats_compress", "none") == "none"
        ):
            return True
    return False


def _pallas_forces_vma_off(*models: nnx.Module) -> bool:
    """Should the VMA checker be dropped because Pallas BN kernels will
    trace for one of ``models``?

    Scoped to the INTERPRET lowering only: the hlo_interpreter's
    dynamic_slice rejects ``check_vma=True`` around pallas bodies on the
    CPU test mesh (the round-3 observation that motivated the blanket
    concession). The real TPU lowering keeps the checker ON — the very
    checker that caught round 1's 8x-gradient bug — pending the
    ``vma_probe`` battery stage recording a TPU-lowering rejection, which
    would be the evidence to widen this again."""
    from tpu_syncbn.ops import _pallas_common

    if not _pallas_common.interpret():
        return False
    return any(_model_traces_pallas_bn(m) for m in models)


def _rewire_syncbn_axes(model: nnx.Module, axes: tuple) -> None:
    """Point every default-axis SyncBatchNorm at the composed layout's
    stat axes: the paper's contract is that BN statistics sync over ALL
    batch replicas, and a composed layout shards the batch over more
    than one mesh axis — a module still syncing over ``'data'`` alone
    would compute partial statistics. Modules carrying a non-default
    axis are left alone (deliberate sub-world scoping)."""
    from tpu_syncbn.nn.normalization import SyncBatchNorm

    for _, node in nnx.iter_graph(model):
        if isinstance(node, SyncBatchNorm) and node.axis_name == DATA_AXIS:
            if node.group_size is not None:
                raise ValueError(
                    "group-scoped SyncBN cannot ride a composed layout: "
                    "the butterfly group reduction is single-axis "
                    f"(module syncs groups of {node.group_size})"
                )
            node.axis_name = axes


def _stats_replicated_by_construction(model: nnx.Module) -> bool:
    """True when every non-Param Variable in the model is owned by a
    full-world SyncBatchNorm: such stats are computed from psum'd global
    moments, hence bit-identical on every replica — a per-step buffer
    broadcast would be a pure waste of ICI bandwidth.

    Conservative on purpose: the per-step broadcast legalizes ALL of the
    ``rest`` state (anything non-Param), so any leaf whose owner is not a
    full-world SyncBatchNorm — group-scoped SyncBN, plain BN, RNG state,
    custom mutable Variables, stats nested in containers — keeps DDP's
    broadcast-from-replica-0."""
    from tpu_syncbn.nn.normalization import SyncBatchNorm

    modules: dict[tuple, nnx.Module] = {}
    var_paths: list[tuple] = []
    for path, node in nnx.iter_graph(model):
        if isinstance(node, nnx.Module):
            modules[tuple(path)] = node
        elif isinstance(node, nnx.Variable) and not isinstance(node, nnx.Param):
            var_paths.append(tuple(path))
    for vpath in var_paths:
        owner = None
        for k in range(len(vpath), -1, -1):
            if vpath[:k] in modules:
                owner = modules[vpath[:k]]
                break
        if not isinstance(owner, SyncBatchNorm) or owner.group_size is not None:
            return False
    return True




@dataclasses.dataclass
class StepOutput:
    """What a compiled train step returns to the host.

    ``monitors`` carries the on-device health scalars
    (``obs.stepstats``: grad global-norm, non-finite counts, BN
    running-stat health) computed inside the compiled step — they are
    ordinary async step outputs, so reading the struct costs no extra
    device sync until a value is actually fetched."""

    loss: jax.Array
    metrics: dict[str, jax.Array]
    monitors: dict[str, jax.Array] = dataclasses.field(default_factory=dict)


class DataParallel:
    """Compiled data-parallel training for an nnx model over the ``data``
    mesh axis — the reference's step 4
    (``ddp_net = nn.parallel.DistributedDataParallel(net, ...)``,
    ``README.md:67-71``) as a step-factory.

    Usage (the recipe's loop, ``README.md:57-60``)::

        model = convert_sync_batchnorm(Net(rngs))
        dp = DataParallel(model, optax.sgd(1e-2), loss_fn)
        for epoch in range(E):
            sampler.set_epoch(epoch)
            for batch in device_prefetch(iter(loader), sharding=dp.batch_sharding):
                out = dp.train_step(batch)       # loss already pmean'd
        dp.sync_to_model()                        # pull state back into `model`

    ``loss_fn(model, batch)`` returns a scalar local-mean loss or
    ``(loss, metrics_dict)``. Gradients are ``pmean``'d across replicas, so
    with equal shards (``drop_last=True``, ``README.md:90``) the update
    equals single-device large-batch SGD — DDP's contract.

    ``accum_steps > 1`` reproduces DDP's ``no_sync()`` pattern: the local
    batch is split into microbatches scanned sequentially with local grad
    accumulation and ONE cross-replica grad reduction at the end
    (``[torch] nn/parallel/distributed.py:1659``).

    ``broadcast_buffers`` (default ``"auto"``): ``True`` broadcasts
    BatchStat buffers from replica 0 inside every step (DDP's default
    ``forward_sync_buffers``, ``:793``), keeping plain-BN buffers
    replicated exactly as DDP does; ``False`` stores buffers honestly
    per-replica. ``"auto"`` detects the converted-model case — every
    stat-owning module a full-world SyncBatchNorm, whose stats are
    already identical on all replicas by construction — and skips the
    per-step broadcast there (XLA cannot fold a value-dependent no-op
    all-reduce, so on hardware the DDP-parity broadcast is a real
    per-step cost), broadcasting otherwise.
    """

    def __init__(
        self,
        model: nnx.Module,
        optimizer: optax.GradientTransformation,
        loss_fn: Callable[[nnx.Module, Any], Any],
        *,
        mesh: Mesh | None = None,
        axis_name: str = DATA_AXIS,
        layout: Any | None = None,
        broadcast_buffers: bool | str = "auto",
        accum_steps: int = 1,
        donate: bool = True,
        remat: bool = False,
        grad_compression: str | None = None,
        compress: str = "none",
        error_feedback: bool | None = None,
        zero: bool = False,
        divergence_guard: str | None = None,
        monitors: bool | str = True,
    ):
        """``remat=True`` rematerializes the forward during backward
        (``jax.checkpoint``) — trades ~1/3 more FLOPs for activation
        memory, the standard HBM-pressure lever on TPU; step numerics are
        unchanged (tested).

        ``grad_compression="bf16"`` casts gradients to bfloat16 for the
        cross-replica all-reduce and back — DDP's
        ``bf16_compress_hook`` communication hook
        (``[torch] distributed/algorithms/ddp_comm_hooks``), halving the
        gradient traffic over ICI/DCN at a small precision cost. This is
        the legacy stateless hook; prefer ``compress=``.

        ``compress`` (default ``"none"``) opts the gradient all-reduce
        into a compressed wire dtype (docs/PERFORMANCE.md "Compressed
        collectives"): ``"bf16"`` halves, ``"int8"`` quarters the bytes
        on the wire (chunk-quantized shared-range s8 AllReduce —
        ``collectives.compressed_psum``). The step's loss/metric pmean
        rides bf16 under any lossy mode (reporting scalars, not training
        state); the divergence guard's pmin/finiteness collective and
        SyncBN's count census ALWAYS stay exact fp32, and SyncBN moment
        stats compress only via their own explicit opt-in
        (``convert_sync_batchnorm(stats_compress=...)``) — never
        implicitly with the gradients.

        ``error_feedback`` (default: on for ``compress="int8"``, off for
        ``"bf16"``) arms the persistent error-feedback residual: each
        replica reduces ``grads + residual`` and re-captures its own
        quantization error, so compression error is re-sent until it
        lands instead of accumulating across steps. The residual is
        per-replica state riding inside ``opt_state`` (like the
        divergence-guard state), so it persists through checkpoints, is
        rolled back on a guarded non-finite step, and is zeroed by
        ``restore_last_good`` rollbacks (``reset_compression_residual``).
        Memory cost: one f32 copy of the gradients per device.

        ``zero=True`` shards parameters and optimizer state across the
        data axis (ZeRO; beyond reference scope — DDP replicates both,
        ``[torch] nn/parallel/distributed.py:466``). Params live as
        dtype-grouped flat vectors sharded 1/world per device; each step
        all_gathers params once, ``psum_scatter``s the flat gradients
        (same wire cost as DDP's all-reduce, since all-reduce =
        reduce-scatter + all-gather), and the optimizer touches only the
        local shard — Adam's f32 moments never exist in full on any
        device. Numerics are identical to ``zero=False`` for
        *elementwise* optimizer transforms (SGD/momentum/Adam/AdamW,
        schedules, per-leaf clipping); transforms needing a global view
        across parameters (``clip_by_global_norm``) would compute their
        statistic per-shard and are unsupported under ``zero``.

        ``divergence_guard`` (default ``None``) arms the on-device
        non-finite guard (docs/RESILIENCE.md): every step computes a
        world-consensus "loss and all grads finite" flag; a non-finite
        step NEVER reaches the weights — params, optimizer state, and BN
        buffers are rolled back to their pre-step values inside the
        compiled step (an exact skip, not a zero-grad update: Adam
        moments and step counts are untouched). The policy string picks
        what else happens: ``"skip_step"`` nothing; ``"halve_lr"``
        additionally halves a persistent update scale each non-finite
        step (applied multiplicatively to every subsequent update);
        ``"restore_last_good"`` behaves like skip on-device and signals
        the host loop (``runtime.resilience.ResilientLoop``) to reload
        the last verified checkpoint. The step's metrics gain
        ``nonfinite`` (1.0 on a skipped step) and ``lr_scale``; the
        occurrence count persists in the guard state (and therefore in
        checkpoints).

        ``monitors`` (default ``True``) computes on-device health
        scalars inside the compiled step and returns them through
        ``StepOutput.monitors``: grad global-norm and non-finite count
        (``obs.stepstats.grad_monitors``) plus BN running-stat health
        (``state_health``). ``"full"`` adds per-layer BN buffer
        monitors; ``False`` turns the block off (``monitors == {}``).
        They ride the step's existing outputs — no extra per-step
        host→device syncs (under ``zero`` the grad norm needs one
        scalar device-side psum, since grads exist only as shards).

        Monitors include the numerics drift/compression family
        (``obs.numerics``, docs/OBSERVABILITY.md "Numerics & drift"):
        ``bn_mean_skew``/``bn_var_skew``/``bn_skew_layers`` (per-replica
        BN batch moments vs the synced value), ``replica_grad_norm`` /
        ``replica_grad_norm_disp`` (cross-replica grad-norm dispersion)
        and — on the compressed paths — ``clip_fraction`` /
        ``overflow_headroom`` (int8) and ``ef_residual_ratio`` (error
        feedback). The whole family costs exactly ONE extra fused
        scalar psum per compiled program (device↔device, never a host
        sync), a bound the golden program contracts machine-check."""
        if accum_steps < 1:
            raise ValueError("accum_steps must be >= 1")
        if divergence_guard not in (
            None, "skip_step", "halve_lr", "restore_last_good"
        ):
            raise ValueError(
                "divergence_guard must be None, 'skip_step', 'halve_lr', "
                f"or 'restore_last_good', got {divergence_guard!r}"
            )
        if grad_compression not in (None, "bf16"):
            raise ValueError(
                f"grad_compression must be None or 'bf16', got {grad_compression!r}"
            )
        collectives.check_compress_mode(compress)
        if grad_compression is not None and compress != "none":
            raise ValueError(
                "grad_compression (legacy bf16 hook) and compress are "
                "mutually exclusive — use compress='bf16'"
            )
        self.compress = compress
        if error_feedback and compress == "none":
            raise ValueError(
                "error_feedback=True needs a lossy compress mode "
                "('bf16'/'int8') — there is no compression error to "
                "feed back on the exact fp32 wire"
            )
        #: error feedback defaults on only where the quantization error
        #: is large enough to matter (int8's shared-range budget); bf16
        #: rounding is benign and the residual costs params-sized f32
        #: state per device
        self._ef = compress != "none" and (
            error_feedback if error_feedback is not None
            else compress == "int8"
        )
        if broadcast_buffers not in (True, False, "auto"):
            raise ValueError(
                "broadcast_buffers must be True, False, or 'auto', got "
                f"{broadcast_buffers!r}"
            )
        if monitors not in (True, False, "full"):
            raise ValueError(
                f"monitors must be True, False, or 'full', got {monitors!r}"
            )
        self.monitors = monitors
        self.remat = remat
        self.grad_compression = grad_compression
        self._model = model
        from tpu_syncbn.parallel.layout import SpecLayout

        # The SpecLayout owns the mesh and every derived reduce/scatter
        # axis (ROADMAP item 1). The legacy kwargs remain the
        # single-axis surface: no layout → plain DP (or the ZeRO preset
        # when zero=True) on the historical 1-D data mesh, byte-identical
        # programs. A composed layout (SpecLayout.fsdp(...)) shards the
        # batch over P(('data','fsdp')) and the flat param/opt store over
        # the fsdp axis only.
        if layout is None:
            if mesh is not None:
                layout = SpecLayout.from_mesh(
                    mesh, param_shard_axis=axis_name if zero else "auto"
                )
            elif zero:
                layout = SpecLayout.zero()
            else:
                layout = SpecLayout.data_parallel()
        else:
            if mesh is not None and mesh != layout.mesh:
                raise ValueError(
                    "pass either layout= or mesh=, not both — the layout "
                    "owns the mesh"
                )
            if zero and layout.param_shard_axis is None:
                raise ValueError(
                    "zero=True needs a param-sharding layout: use "
                    "SpecLayout.zero() or SpecLayout.fsdp()"
                )
        layout.check(compress=compress)
        if layout.rules:
            if monitors:
                raise ValueError(
                    "tensor-parallel param rules currently require "
                    "monitors=False (the grad monitors assume replicated "
                    "or flat-sharded params)"
                )
            if self._ef:
                raise ValueError(
                    "tensor-parallel param rules do not compose with "
                    "error feedback (the residual store assumes "
                    "replicated param shapes) — pass error_feedback=False"
                )
        self.layout = layout
        self.mesh = layout.mesh
        #: the mesh axis — or tuple of axes under a composed layout —
        #: every batch-scoped reduction (grad reduce, SyncBN stats,
        #: loss/metric pmean, guard consensus) runs over
        self.axis_name = (
            layout.stat_axes if layout.stat_axes is not None else axis_name
        )
        if isinstance(self.axis_name, tuple):
            _rewire_syncbn_axes(model, self.axis_name)
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.accum_steps = accum_steps
        if broadcast_buffers == "auto":
            # replicated storage either way; skip the per-step broadcast
            # when the stats are replicated by construction
            self._per_step_broadcast = not _stats_replicated_by_construction(
                model
            )
            broadcast_buffers = True
        else:
            self._per_step_broadcast = bool(broadcast_buffers)
        self.broadcast_buffers = broadcast_buffers
        # VMA checker on, EXCEPT when the Pallas BN kernels will trace
        # for THIS model *under the interpret lowering* (CPU test mesh),
        # whose dynamic_slice rejects the checker regardless of kernel
        # correctness. On TPU the checker stays on even with Pallas
        # bodies. With the checker off, replication is guaranteed
        # structurally, exactly as in round 1. Snapshotted at
        # construction — set_pallas_mode() must be called before building
        # the trainer (its docstring says so). On pre-VMA jax
        # (compat.HAS_VMA False) there is no checker and no cast to
        # drive: stay off.
        self._check_vma = compat.HAS_VMA and not _pallas_forces_vma_off(model)

        self.zero = layout.param_shard_axis is not None
        self.graphdef, params, rest = nnx.split(model, nnx.Param, ...)
        self.rest = rest  # BatchStats + any other non-Param state

        self.batch_sharding = layout.batch_sharding
        self._replicated = layout.replicated
        self._per_replica = layout.sharding(P(self.axis_name))
        #: total batch replicas — the gradient-mean divisor; the product
        #: of the batch axes under a composed layout
        self.world = layout.replica_world
        #: flat param/opt shard axis and its size (ZeRO/FSDP): the whole
        #: data axis for the zero preset, the dedicated fsdp axis when
        #: composed
        self._shard_axis = layout.grad_scatter_axis
        self._shard_world = layout.shard_world
        #: batch axes left to psum after the gradient reduce-scatter
        self._cross_axes = layout.grad_cross_axes

        # put state on the mesh once. Params/opt replicated (or flat +
        # 1/world-sharded under zero); buffers replicated when
        # broadcast_buffers keeps them in sync, otherwise stored honestly
        # per-replica ((world, ...) sharded on the data axis) — torch's
        # broadcast_buffers=False keeps local buffers per replica, and
        # declaring divergent buffers "replicated" would let any host
        # read return an arbitrary replica's stats.
        if self.zero:
            from tpu_syncbn.parallel.zero import FlatLayout, check_elementwise

            check_elementwise(optimizer)
            self._layout = FlatLayout(params, self._shard_world)
            self._pspec = {
                dt: P(self._shard_axis) for dt in self._layout.groups
            }
            self._store_sharding = layout.sharding(P(self._shard_axis))
            self._param_store = jax.device_put(
                self._layout.flatten(params), self._store_sharding
            )
            # optimizer state is born sharded: init runs per-shard under
            # shard_map; vector leaves (moments etc., shaped like the
            # shard) shard along the axis, scalar leaves (step counts)
            # replicate.
            shard_tpl = {
                dt: jax.ShapeDtypeStruct((n,), jnp.dtype(dt))
                for dt, n in self._layout.shard_sizes.items()
            }
            opt_shapes = jax.eval_shape(optimizer.init, shard_tpl)
            self._opt_spec = jax.tree_util.tree_map(
                lambda l: P() if l.ndim == 0 else P(self._shard_axis),
                opt_shapes,
            )
            init_sharded = shard_map(
                optimizer.init,
                mesh=self.mesh,
                in_specs=(self._pspec,),
                out_specs=self._opt_spec,
                check_vma=self._check_vma,
            )
            self.opt_state = jax.jit(init_sharded)(self._param_store)
        elif layout.rules:
            # tensor-parallel rules: per-param specs from the layout's
            # wildcard matching; the optimizer state inherits the param
            # shardings through the compiler (elementwise init
            # propagates input shardings; scalar leaves replicate)
            self._pspec = layout.param_specs(params)
            shardings = jax.tree_util.tree_map(
                layout.sharding, self._pspec,
                is_leaf=lambda x: isinstance(x, P),
            )
            self._param_store = jax.device_put(params, shardings)
            self.opt_state = jax.jit(
                optimizer.init, in_shardings=(shardings,)
            )(self._param_store)
            self._opt_spec = jax.tree_util.tree_map(
                lambda a: a.sharding.spec, self.opt_state
            )
        else:
            self._pspec = P()
            self._opt_spec = P()
            self._param_store = jax.device_put(params, self._replicated)
            self.opt_state = jax.device_put(
                optimizer.init(params), self._replicated
            )
        self.divergence_guard = divergence_guard
        if divergence_guard is not None:
            # guard state rides inside opt_state so every existing code
            # path (donation, scan carries, state_dict/load, shard specs)
            # treats it as optimizer state — which semantically it is:
            # per-update bookkeeping that must survive checkpoints
            guard0 = jax.device_put(
                {
                    "lr_scale": jnp.ones((), jnp.float32),
                    "nonfinite_count": jnp.zeros((), jnp.int32),
                },
                self._replicated,
            )
            self.opt_state = (self.opt_state, guard0)
            if self.zero:
                self._opt_spec = (
                    self._opt_spec,
                    {"lr_scale": P(), "nonfinite_count": P()},
                )
            # non-zero mode: _opt_spec is the single prefix spec P(),
            # which covers the (opt_state, guard) tuple unchanged
        if self._ef:
            # error-feedback residual rides OUTSIDE the guard wrap in
            # opt_state: (inner_opt[, guard], residual). Per-replica
            # state (every replica's quantization error differs), stored
            # honestly with a leading world axis sharded on the data
            # axis — the broadcast_buffers=False storage pattern.
            if self.zero:
                res0 = {
                    dt: jnp.zeros(
                        (self.world,
                         n if jnp.issubdtype(jnp.dtype(dt), jnp.floating)
                         else 0),
                        jnp.float32,
                    )
                    for dt, n in self._layout.padded.items()
                }
            else:
                res0 = jax.tree_util.tree_map(
                    lambda z: jnp.zeros((self.world,) + z.shape, z.dtype),
                    collectives.init_error_feedback(params),
                )
            self.opt_state = (
                self.opt_state, jax.device_put(res0, self._per_replica)
            )
            # self.axis_name, not the ctor arg: under a composed layout
            # the per-replica store spans ALL batch axes — a 'data'-only
            # spec would silently share residuals across the fsdp axis
            # (and shrink the stored leading dim, breaking state_dict)
            self._opt_spec = (self._opt_spec, P(self.axis_name))
        if broadcast_buffers:
            self.rest = jax.device_put(self.rest, self._replicated)
        else:
            self.rest = jax.device_put(
                jax.tree_util.tree_map(
                    lambda x: jnp.broadcast_to(x[None], (self.world,) + x.shape),
                    self.rest,
                ),
                self._per_replica,
            )
        self._rest_spec = P() if broadcast_buffers else P(self.axis_name)

        self._donate = donate
        self._train_step = self._build_train_step(donate)
        # first-dispatch compile latch (obs.profiling): the jit above
        # compiles on its first call, which is a compile seam the
        # recompile-storm detector must see (a hot weight swap that
        # rebuilds the trainer re-pays it)
        self._first_dispatch_noted = False
        from tpu_syncbn.parallel import scan_driver

        # n_steps -> scanned jit (FIFO-bounded, hit/miss/eviction counted)
        self._train_steps_cache = scan_driver.ProgramCache(name="train")
        # compress mode -> parked (jit, scan cache, compile latch):
        # set_compress() swaps whole program sets so a mode revisited
        # mid-run reuses its already-compiled executables
        self._mode_programs: dict[str, tuple] = {}
        self._eval_step = self._build_eval_step()

    # -- step builders ----------------------------------------------------

    def _microbatch_grads(self, params, rest, batch):
        """value_and_grad over one microbatch; returns (loss, metrics,
        new_rest, grads, numx) — ``numx`` being the numerics drift
        scalars (BN batch-moment skew vs the synced value) the forward's
        SyncBN reductions recorded under the monitor collector; ``{}``
        with monitors off, so the traced program is unchanged."""
        collect_numerics = bool(self.monitors)

        def lossed(p, r, b):
            # copy=True: fresh trace-local Variables, so BN's BatchStat
            # mutation happens at this trace level (nnx 0.12 merge
            # otherwise aliases the original module's variables)
            model = compat.nnx_merge(self.graphdef, p, r, copy=True)
            model.train()
            # the skew scalars are traced INSIDE the differentiated
            # function, so they must exit through its aux (a module-level
            # side channel would leak VJP-trace tracers)
            with obs_numerics.collect(enabled=collect_numerics) as col:
                out = self.loss_fn(model, b)
            loss, metrics = out if isinstance(out, tuple) else (out, {})
            _, _, new_r = nnx.split(model, nnx.Param, ...)
            return loss, (metrics, new_r, col.summary())

        if self.remat:
            lossed = jax.checkpoint(lossed)
        # Cast replicated params to device-varying OUTSIDE the
        # differentiated function. Under shard_map's VMA type system an
        # *unvarying* param meeting varying data gets an implicit pvary
        # whose transpose is a psum — value_and_grad would then return
        # grads already summed across replicas, and the explicit pmean
        # below would double-count by the world size (the "8x off"
        # discrepancy of round 1). With the cast outside the VJP, grads
        # stay local and the explicit pmean is the one aggregation —
        # DDP's semantics, and check_vma=True validates the whole step.
        # (With the checker off — pallas mode — grads are local anyway.)
        if self._check_vma:
            params = _pcast_varying(params, self.axis_name)
        (loss, (metrics, new_rest, numx)), grads = jax.value_and_grad(
            lossed, has_aux=True
        )(params, rest, batch)
        return loss, metrics, new_rest, grads, numx

    def _gather_params(self, store):
        """ZeRO/FSDP path: rebuild the full param tree from this
        device's flat shards — ONE all_gather per dtype group, over the
        shard axis only (a composed layout's data axis already holds the
        value replicated)."""
        full = {
            dt: collectives.all_gather(v, self._shard_axis, axis=0, tiled=True)
            for dt, v in store.items()
        }
        return self._layout.unflatten(full)

    def _build_train_step(self, donate: bool):
        step = self._make_step_fn()
        sharded = shard_map(
            step,
            mesh=self.mesh,
            in_specs=(self._pspec, self._rest_spec, self._opt_spec,
                      P(self.axis_name)),
            out_specs=(self._pspec, self._rest_spec, self._opt_spec,
                       P(), P(), P()),
            # VMA checker ON (unless pallas traces — see __init__):
            # validates that params/opt_state/loss really are replicated
            # after the step. Requires the explicit varying-cast of params
            # in _microbatch_grads — see the comment there for the
            # round-1 "8x off" root cause.
            check_vma=self._check_vma,
        )
        donate_argnums = (0, 1, 2) if donate else ()
        return jax.jit(sharded, donate_argnums=donate_argnums)

    def _make_step_fn(self):
        """The pure per-device step body (params, rest, opt_state, batch)
        -> (params, rest, opt_state, loss, metrics) — shared by the
        single-step jit and the scanned multi-step jit (``train_steps``);
        its in/out trees keep a stable VMA type, which is what makes it a
        legal ``lax.scan`` carry."""
        axis = self.axis_name

        def step(pstore, rest, opt_state, batch):
            monitors: dict = {}
            guard_in = None
            ef_in = ef_out = None
            if self._ef:
                # residual rides outermost in opt_state; strip the
                # per-replica storage axis of 1 (like honest buffers)
                opt_state, ef_stored = opt_state
                ef_in = jax.tree_util.tree_map(lambda x: x[0], ef_stored)
            if self.divergence_guard is not None:
                opt_state, guard_in = opt_state
            pstore_in, opt_in = pstore, opt_state
            params = self._gather_params(pstore) if self.zero else pstore
            if not self.broadcast_buffers:
                # per-replica storage: strip the local leading axis of 1
                rest = jax.tree_util.tree_map(lambda x: x[0], rest)
            rest_in = rest
            if self.accum_steps == 1:
                loss, metrics, rest, grads, numx = self._microbatch_grads(
                    params, rest, batch
                )
            else:
                # no_sync() pattern: scan microbatches, accumulate local
                # grads, single cross-replica reduction afterwards
                local_bs = jax.tree_util.tree_leaves(batch)[0].shape[0]
                if local_bs % self.accum_steps:
                    raise ValueError(
                        f"per-replica batch size {local_bs} is not divisible "
                        f"by accum_steps={self.accum_steps}"
                    )
                micro = jax.tree_util.tree_map(
                    lambda x: x.reshape(
                        (self.accum_steps, x.shape[0] // self.accum_steps)
                        + x.shape[1:]
                    ),
                    batch,
                )

                # scan carries must keep a stable VMA type: local grads are
                # device-varying, and BN stats flip between unvarying
                # (SyncBN: psum'd) and varying (plain BN). Pin the grad
                # accumulator to varying always; pin the buffer carry to
                # varying only when a post-scan broadcast (or per-replica
                # out-spec) will legalize it — in the skip-broadcast mode
                # the stats stay unvarying through every iteration.
                if self._check_vma:
                    def to_varying(tree):
                        return _pcast_varying(tree, axis)
                else:
                    def to_varying(tree):
                        return tree

                pin_rest = self._per_step_broadcast or not self.broadcast_buffers

                def body(carry, mb):
                    rest, acc = carry
                    loss, metrics, rest, grads, numx = (
                        self._microbatch_grads(params, rest, mb)
                    )
                    acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                    rest = to_varying(rest) if pin_rest else rest
                    return (rest, acc), (loss, metrics, numx)

                zero = to_varying(
                    jax.tree_util.tree_map(jnp.zeros_like, params)
                )
                rest = to_varying(rest) if pin_rest else rest
                (rest, grads), (losses, metricses, numxes) = jax.lax.scan(
                    body, (rest, zero), micro
                )
                grads = jax.tree_util.tree_map(
                    lambda g: g / self.accum_steps, grads
                )
                loss = jnp.mean(losses)
                metrics = jax.tree_util.tree_map(jnp.mean, metricses)
                # worst microbatch wins: skew anywhere in the accum
                # window is drift (same fold as Collector.summary)
                numx = jax.tree_util.tree_map(
                    lambda a: jnp.max(a, axis=0), numxes
                )

            if self.compress != "none":
                # reporting scalars ride the wire in bf16 under any
                # lossy mode — they are telemetry, not training state
                loss = collectives.compressed_pmean(loss, axis, mode="bf16")
                metrics = collectives.compressed_pmean(
                    metrics, axis, mode="bf16"
                )
            else:
                loss = collectives.pmean(loss, axis)
                metrics = collectives.pmean(metrics, axis)

            ok = None
            if guard_in is not None:
                # world-consensus finiteness: the pmean'd loss catches a
                # NaN loss on ANY replica, but grads can blow up (inf in
                # the backward) with a finite loss — and a replica-local
                # verdict would let replicas take different branches and
                # diverge. pmin over the local flags is the consensus.
                gfin = jnp.bool_(True)
                for leaf in jax.tree_util.tree_leaves(grads):
                    gfin &= jnp.all(jnp.isfinite(leaf))
                gfin = collectives.pmin(gfin.astype(jnp.int32), axis) > 0
                ok = jnp.isfinite(loss) & gfin

            if self.zero:
                # average + shard the gradients in ONE collective: a
                # psum_scatter is the reduce-scatter half of the
                # all-reduce DDP would issue, and the optimizer only
                # needs this device's shard
                flat_g = self._layout.flatten(grads)
                new_ef: dict = {}
                if self.monitors:
                    # per-replica grad norm BEFORE the reduce-scatter:
                    # the local half of the dispersion monitor
                    numx["replica_grad_norm"] = (
                        obs_numerics.grad_norm_scalar(flat_g)
                    )
                ccol_ctx = obs_numerics.collect(enabled=bool(self.monitors))

                shard_axis = self._shard_axis
                cross = self._cross_axes

                def scatter(dt, g):
                    floating = jnp.issubdtype(g.dtype, jnp.floating)
                    if self.compress != "none" and floating:
                        # compressed reduce-scatter (one quantization
                        # chunk per scatter shard); with EF the residual
                        # is re-sent with the next step's gradients
                        p = g.astype(jnp.float32)
                        if self._ef:
                            p = p + ef_in[dt]
                        shard, res = collectives.compressed_reduce_scatter(
                            p, shard_axis, mode=self.compress,
                            want_residual=self._ef,
                        )
                        if self._ef:
                            new_ef[dt] = res
                        if cross:
                            # composed layout: finish the reduction over
                            # the remaining batch axes on the 1/F shard
                            # — the wire bytes were already cut by the
                            # scatter, and the compressed wire stays
                            # legal over the cross axes. (EF covers the
                            # scatter stage only; the cross stage's
                            # quantization error is unfed — int8
                            # composed is convergence-tested, not
                            # bit-parity-pinned.)
                            shard = collectives.compressed_psum(
                                shard, cross, mode=self.compress
                            )
                        return (shard / self.world).astype(g.dtype)
                    if self._ef:
                        new_ef[dt] = ef_in[dt]  # exact group: no error
                    if self.grad_compression == "bf16":
                        d = g.dtype
                        g = collectives.reduce_scatter(
                            g.astype(jnp.bfloat16), shard_axis
                        ).astype(d)
                    else:
                        g = collectives.reduce_scatter(g, shard_axis)
                    if cross:
                        # exact completion of the mean over the other
                        # batch axes, on shard-sized operands
                        g = collectives.psum(g, cross)
                    return g / self.world

                with ccol_ctx as ccol:
                    # the compressed reduce-scatters record their int8
                    # clip fraction / overflow headroom into the active
                    # collector (parallel.collectives)
                    gshard = {dt: scatter(dt, g) for dt, g in flat_g.items()}
                if self._ef:
                    ef_out = new_ef
                if self.monitors:
                    numx.update(ccol.summary())
                    if self._ef:
                        numx["ef_residual_ratio"] = obs_numerics.residual_ratio(
                            new_ef, numx["replica_grad_norm"]
                        )
                    # shards only: one scalar device-side psum (over the
                    # shard axis — the cross axes already hold the
                    # reduced value replicated) globalizes
                    monitors.update(obs_stepstats.grad_monitors(
                        gshard, shard_axis, sharded=True
                    ))
                updates, opt_state = self.optimizer.update(
                    gshard, opt_state, pstore
                )
                if (self.divergence_guard == "halve_lr"
                        and guard_in is not None):
                    updates = jax.tree_util.tree_map(
                        lambda u: u * guard_in["lr_scale"], updates
                    )
                pstore = optax.apply_updates(pstore, updates)
            else:
                if self.monitors:
                    # per-replica grad norm BEFORE the all-reduce: the
                    # local half of the dispersion monitor
                    numx["replica_grad_norm"] = (
                        obs_numerics.grad_norm_scalar(grads)
                    )
                # DDP gradient averaging: one compiler-scheduled
                # all-reduce; the compressed paths record their int8
                # clip fraction / overflow headroom into the collector
                with obs_numerics.collect(
                    enabled=bool(self.monitors)
                ) as ccol:
                    if self._ef:
                        grads, ef_out = collectives.ef_compressed_pmean(
                            grads, ef_in, axis, mode=self.compress
                        )
                    elif self.compress != "none":
                        grads = collectives.compressed_pmean(
                            grads, axis, mode=self.compress
                        )
                    elif self.grad_compression == "bf16":
                        # bf16_compress_hook parity: halve the wire traffic
                        dtypes = jax.tree_util.tree_map(
                            lambda g: g.dtype, grads
                        )
                        grads = jax.tree_util.tree_map(
                            lambda g: g.astype(jnp.bfloat16), grads
                        )
                        grads = collectives.pmean(grads, axis)
                        grads = jax.tree_util.tree_map(
                            lambda g, d: g.astype(d), grads, dtypes
                        )
                    else:
                        grads = collectives.pmean(grads, axis)
                if self.monitors:
                    numx.update(ccol.summary())
                    if self._ef:
                        numx["ef_residual_ratio"] = (
                            obs_numerics.residual_ratio(
                                ef_out, numx["replica_grad_norm"]
                            )
                        )
                    # post-pmean grads are replicated: pure arithmetic,
                    # no collective needed
                    monitors.update(obs_stepstats.grad_monitors(grads))
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params
                )
                if (self.divergence_guard == "halve_lr"
                        and guard_in is not None):
                    updates = jax.tree_util.tree_map(
                        lambda u: u * guard_in["lr_scale"], updates
                    )
                pstore = optax.apply_updates(params, updates)

            if self.monitors and numx:
                # numerics drift/compression monitors (obs.numerics): the
                # per-replica local scalars — BN batch-moment skew, local
                # grad norm, int8 clip/headroom, EF residual ratio — fused
                # into ONE scalar psum. That single collective is the
                # monitors' whole wire cost, pinned by the golden program
                # contracts and tests/test_numerics.py's one-psum gate.
                monitors.update(obs_numerics.cross_replica_monitors(
                    numx, axis, disp_keys=("replica_grad_norm",),
                    varying_cast=self._check_vma,
                ))

            if guard_in is not None:
                # exact skip of a non-finite step: params, optimizer
                # state, and BN buffers all roll back to their pre-step
                # values — jnp.where never propagates the not-taken
                # branch's NaNs
                def sel(new, old):
                    return jax.tree_util.tree_map(
                        lambda n, o: jnp.where(ok, n, o.astype(n.dtype)),
                        new, old,
                    )

                pstore = sel(pstore, pstore_in)
                opt_state = sel(opt_state, opt_in)
                rest = sel(rest, rest_in)
                if ef_out is not None:
                    # a skipped step must not consume the residual: the
                    # gradients it absorbed never reached the weights
                    ef_out = sel(ef_out, ef_in)
                notok_i = 1 - ok.astype(jnp.int32)
                lr_scale = guard_in["lr_scale"]
                if self.divergence_guard == "halve_lr":
                    lr_scale = jnp.where(ok, lr_scale, lr_scale * 0.5)
                guard_out = {
                    "lr_scale": lr_scale,
                    "nonfinite_count":
                        guard_in["nonfinite_count"] + notok_i,
                }
                metrics = {
                    **metrics,
                    "nonfinite": notok_i.astype(jnp.float32),
                    "lr_scale": guard_in["lr_scale"],
                }
                opt_state = (opt_state, guard_out)

            if self.broadcast_buffers:
                if self._per_step_broadcast:
                    # per-step buffer broadcast (DDP forward_sync_buffers
                    # :793)
                    rest = collectives.broadcast(rest, src=0, axis_name=axis)
                # else: full-world SyncBN stats are replicated by
                # construction (psum'd moments) — already unvarying, and
                # an explicit broadcast would be a wasted all-reduce
                if self.monitors:
                    # post-broadcast (or by-construction-replicated)
                    # buffers: pure arithmetic yields replicated monitors
                    monitors.update(obs_stepstats.state_health(
                        rest, per_layer=self.monitors == "full"
                    ))
            else:
                if self.monitors:
                    # per-replica buffers: reduce to the worst replica so
                    # the monitors stay legal replicated outputs
                    monitors.update(obs_stepstats.state_health(
                        rest, axis, reduce=True,
                        per_layer=self.monitors == "full",
                    ))
                # re-stack for honest per-replica storage (P(axis) output:
                # declare varying even when SyncBN stats are replicated)
                if self._check_vma:
                    rest = _pcast_varying(rest, axis)
                rest = jax.tree_util.tree_map(lambda x: x[None], rest)
            if self._ef:
                # re-stack the per-replica residual (honest P(data)
                # storage, stable scan carry) and re-wrap outermost
                if self._check_vma:
                    ef_out = _pcast_varying(ef_out, axis)
                ef_out = jax.tree_util.tree_map(lambda x: x[None], ef_out)
                opt_state = (opt_state, ef_out)
            return pstore, rest, opt_state, loss, metrics, monitors

        return step

    def _build_train_steps(self, n_steps: int, *, stacked: bool = False):
        """``n_steps`` optimizer steps in ONE compiled program:
        ``lax.scan`` of the step body (``parallel.scan_driver`` is the
        shared builder — GANTrainer compiles through the same one).

        The idiomatic TPU training-loop shape (the step loop lives
        on-device; the chip never waits on the host between steps).
        Measured against the host loop on real hardware the two are
        within 1% here — JAX's async dispatch keeps the chip fed even
        through this project's high-latency tunnel
        (``benchmarks/artifacts/tpu_scan_dispatch.json``) — so this is
        an equivalence-proven alternative, not a speedup on this
        hardware; it matters where dispatch IS the bottleneck (many tiny
        steps, slow hosts, multi-process contention). The step body's
        stable VMA-typed in/out trees (see ``_make_step_fn``) are what
        make it a legal scan carry."""
        from tpu_syncbn.parallel import scan_driver

        return scan_driver.build_scan_steps(
            self._make_step_fn(),
            mesh=self.mesh,
            state_specs=(self._pspec, self._rest_spec, self._opt_spec),
            batch_specs=(P(self.axis_name),),
            out_specs=(P(), P(), P()),
            n_steps=n_steps,
            stacked=stacked,
            check_vma=self._check_vma,
            donate=self._donate,
        )

    def _run_scanned(self, key, batch) -> StepOutput:
        from tpu_syncbn.parallel import scan_driver

        n_steps, stacked = key
        fn = scan_driver.cached_program(
            self._train_steps_cache,
            # repeat-mode keys stay plain ints (the historical cache
            # shape); stacked programs key on the pair
            n_steps if not stacked else key,
            lambda: self._build_train_steps(n_steps, stacked=stacked),
        )
        (
            self._param_store,
            self.rest,
            self.opt_state,
            losses,
            metrics,
            monitors,
        ) = fn(self._param_store, self.rest, self.opt_state, batch)
        return StepOutput(loss=losses, metrics=metrics, monitors=monitors)

    def train_steps(self, batch, n_steps: int) -> StepOutput:
        """Run ``n_steps`` optimizer steps on the SAME global batch in
        one compiled program (on-device ``lax.scan`` — no per-step host
        dispatch). Returns per-step stacked ``loss``/``metrics`` of
        leading dimension ``n_steps``.

        For distinct data per step use :meth:`train_steps_batches` with
        a staged chunk (``data.device_prefetch(scan_steps=K)``), or the
        ordinary ``train_step`` host loop; this entry point is for
        dispatch-free inner loops on one batch and honest
        device-throughput measurement.

        Each distinct ``n_steps`` compiles (and caches) its own XLA
        program — call it with a FIXED n; the cache holds the most
        recent few and evicts beyond that, so a varying n pays a fresh
        compile every call."""
        if n_steps < 1:
            raise ValueError(f"n_steps must be >= 1, got {n_steps}")
        return self._run_scanned((n_steps, False), batch)

    @property
    def scan_batch_sharding(self):
        """Sharding for a K-stacked batch (leading scan axis unsharded,
        per-step batch axis over the mesh) — what
        :meth:`train_steps_batches` expects and
        ``data.device_prefetch(scan_steps=K, sharding=dp.batch_sharding)``
        produces."""
        from tpu_syncbn.parallel import scan_driver

        return self.layout.sharding(
            scan_driver.stack_batch_spec(P(self.axis_name))
        )

    def train_steps_batches(self, batches) -> StepOutput:
        """Run one optimizer step per leading-axis slice of ``batches``
        — a pytree stacked to ``(K, global_batch, ...)``, e.g. one
        staged chunk from ``data.device_prefetch(scan_steps=K)`` — in
        ONE compiled program (``lax.scan``; one host dispatch per K
        steps, docs/PERFORMANCE.md). Returns stacked per-step
        ``loss``/``metrics``/``monitors`` of leading dimension K.

        Exactly K sequential ``train_step`` calls on the K slices:
        params, optimizer state, BN buffers, the divergence guard's
        rollbacks, and the monitors all match the step-by-step loop
        (tests/test_scan_driver.py pins this across DataParallel, ZeRO
        mode, and GANTrainer). The chunk itself is never donated — the
        staging queue may still own its buffer."""
        from tpu_syncbn.parallel import scan_driver

        k = scan_driver.scan_length(batches)
        if k < 1:
            raise ValueError(f"stacked batch needs a leading axis >= 1, got {k}")
        return self._run_scanned((k, True), batches)

    def _build_eval_step(self):
        def step(pstore, rest, batch):
            params = self._gather_params(pstore) if self.zero else pstore
            if not self.broadcast_buffers:
                rest = jax.tree_util.tree_map(lambda x: x[0], rest)
            model = compat.nnx_merge(self.graphdef, params, rest, copy=True)
            model.eval()
            out = self.loss_fn(model, batch)
            loss, metrics = out if isinstance(out, tuple) else (out, {})
            loss = collectives.pmean(loss, self.axis_name)
            metrics = collectives.pmean(metrics, self.axis_name)
            return loss, metrics

        sharded = shard_map(
            step,
            mesh=self.mesh,
            in_specs=(self._pspec, self._rest_spec, P(self.axis_name)),
            out_specs=(P(), P()),
            check_vma=self._check_vma,
        )
        return jax.jit(sharded)

    # -- public API -------------------------------------------------------

    @property
    def params(self):
        """The parameter pytree. Under ``zero`` the canonical storage is
        flat + sharded; reading this property assembles the full tree on
        the host (cheap relative to a checkpoint write, the main reader).
        Assigning accepts a param tree in either mode."""
        if self.zero:
            from tpu_syncbn.parallel.zero import unshard_params

            return unshard_params(self._layout, self._param_store)
        return self._param_store

    @params.setter
    def params(self, tree):
        if self.zero:
            self._param_store = jax.device_put(
                self._layout.flatten(tree), self._store_sharding
            )
        else:
            self._param_store = jax.device_put(tree, self._replicated)

    def reset_compression_residual(self) -> bool:
        """Zero the error-feedback residual (no-op without one; returns
        whether there was state to reset). Called by
        ``ResilientLoop._restore_last_good``: after a divergence
        rollback the restored checkpoint's residual encodes compression
        error of a gradient trajectory that has been UNWOUND — re-sending
        it would inject stale updates into the recovered run. Ordinary
        resume keeps the checkpointed residual (it belongs to the
        trajectory being continued)."""
        if not self._ef:
            return False
        inner, ef = self.opt_state
        zero = jax.tree_util.tree_map(jnp.zeros_like, ef)
        self.opt_state = (inner, jax.device_put(zero, self._per_replica))
        return True

    @property
    def program_caches(self) -> tuple:
        """Every scan :class:`~tpu_syncbn.parallel.scan_driver.ProgramCache`
        this trainer owns — the live mode's first, then any parked by
        :meth:`set_compress`. The autopilot's cache-budget actuator
        adjusts ``max_bytes`` on all of them so a parked mode cannot
        hold memory the pressure signal asked back."""
        parked = [
            cache for (_step, cache, _noted) in self._mode_programs.values()
            if cache is not self._train_steps_cache
        ]
        return (self._train_steps_cache, *parked)

    def set_compress(self, mode: str) -> bool:
        """Switch the collective compression wire format at a step
        boundary; returns whether anything changed. The autopilot's
        compression actuator — but equally a manual knob.

        The optimizer-state *structure* is pinned at construction:
        ``self._ef`` (whether an error-feedback residual rides in
        ``opt_state``) never changes here, so checkpoints, fused-scan
        carries, and donation all see one stable pytree across mode
        switches. Under exact modes the residual passes through
        untouched (:func:`collectives.ef_compressed_pmean` with
        ``mode="none"`` degrades to the exact pmean) — construct the
        trainer at the lossiest rung you intend to select (e.g.
        ``compress="int8"``) so the residual exists on every rung.

        Each mode's programs (the per-step jit and the fused-scan
        cache) are parked on switch-away and recalled on switch-back:
        a mode revisited recompiles nothing, which is what keeps the
        recompile-storm detector quiet while the autopilot moves
        between golden-pinned variants. The residual *content* is
        wire-format-specific (int8 quantization error replayed onto a
        bf16 wire is just noise), so it is zeroed at every switch."""
        collectives.check_compress_mode(mode)
        if self.grad_compression is not None:
            raise ValueError(
                "set_compress does not apply to the legacy "
                "grad_compression hook — construct with compress= instead"
            )
        if mode == self.compress:
            return False
        self._mode_programs[self.compress] = (
            self._train_step,
            self._train_steps_cache,
            self._first_dispatch_noted,
        )
        self.compress = mode
        parked = self._mode_programs.get(mode)
        if parked is not None:
            (
                self._train_step,
                self._train_steps_cache,
                self._first_dispatch_noted,
            ) = parked
        else:
            from tpu_syncbn.parallel import scan_driver

            self._train_step = self._build_train_step(self._donate)
            self._train_steps_cache = scan_driver.ProgramCache(name="train")
            self._first_dispatch_noted = False
        self.reset_compression_residual()
        return True

    def train_step(self, batch) -> StepOutput:
        """One optimizer step on a *global* batch (sharded or shardable
        along axis 0 across the mesh)."""
        t0 = time.perf_counter() if not self._first_dispatch_noted else None
        (
            self._param_store,
            self.rest,
            self.opt_state,
            loss,
            metrics,
            monitors,
        ) = self._train_step(self._param_store, self.rest, self.opt_state, batch)
        if t0 is not None:
            # first dispatch = XLA compile (+ one execution, async on
            # real hardware): one compile.train event, time tagged
            self._first_dispatch_noted = True
            from tpu_syncbn.obs import profiling

            profiling.note_compile("train", time.perf_counter() - t0)
        return StepOutput(loss=loss, metrics=metrics, monitors=monitors)

    def eval_step(self, batch) -> StepOutput:
        loss, metrics = self._eval_step(self._param_store, self.rest, batch)
        return StepOutput(loss=loss, metrics=metrics)

    def lowered_train_step(self, batch):
        """AOT-lower the train step for the current state and ``batch``
        without executing it — e.g. ``.cost_analysis()['flops']`` for MFU
        reporting, or ``.as_text()`` for HLO inspection. Keeps the
        (params, rest, opt_state, batch) calling convention private."""
        return self._train_step.lower(
            self._param_store, self.rest, self.opt_state, batch
        )

    def sync_to_model(self) -> nnx.Module:
        """Write the trained state back into the wrapped nnx model (the
        object the user built and may want to eval/save directly) and
        return it. With per-replica buffers (broadcast_buffers=False),
        replica 0's buffers win — matching torch's rank-0 checkpoint
        convention."""
        rest = self.rest
        if not self.broadcast_buffers:
            rest = jax.tree_util.tree_map(lambda x: np.asarray(x)[0], rest)
        nnx.update(self._model, self.params, rest)
        return self._model

    @property
    def model(self) -> nnx.Module:
        return self.sync_to_model()

    # -- checkpointing ----------------------------------------------------

    def state_dict(self) -> dict:
        """Full training state as a pytree (params, buffers, optimizer) —
        feed to utils.checkpoint.save_checkpoint on the master host.

        Returns *copies*: with ``donate=True`` (the default) the live
        buffers are invalidated by the next train_step, so a snapshot that
        merely referenced them would be unreadable afterwards. (Under
        ``zero`` the params property already assembles fresh host arrays
        — copying those again would double the full-model allocation.)"""
        params = self.params
        if not self.zero:
            params = jax.tree_util.tree_map(jnp.copy, params)
        return {
            "params": params,
            "rest": jax.tree_util.tree_map(jnp.copy, self.rest),
            "opt_state": jax.tree_util.tree_map(jnp.copy, self.opt_state),
        }

    def load_state_dict(self, state: dict) -> None:
        """Restore a pytree produced by :meth:`state_dict` (or deserialized
        into its structure), re-placing it on the mesh. The checkpoint
        format is mode-independent for params (always the full tree);
        opt_state is NOT — under ``zero`` its flat vectors carry the
        world-size-dependent padded layout, so resume into a trainer
        built with the same ``zero`` flag AND world size (checked)."""
        want_def = jax.tree_util.tree_structure(self.opt_state)
        got_def = jax.tree_util.tree_structure(state["opt_state"])
        if want_def != got_def:
            raise ValueError(
                "opt_state structure mismatch: this checkpoint was saved "
                "by a trainer with a different optimizer or a different "
                f"`zero` setting than this one (zero={self.zero}). Rebuild "
                "the trainer with the same optimizer and zero flag to "
                "resume the optimizer state."
            )
        if self.zero:
            want = jax.tree_util.tree_map(lambda l: l.shape, self.opt_state)
            got = jax.tree_util.tree_map(
                lambda l: jnp.shape(l), state["opt_state"]
            )
            if want != got:
                raise ValueError(
                    "zero=True opt_state layout mismatch: this checkpoint "
                    "was saved with a different world size (flat shard "
                    "padding is world-dependent). Resume on the same "
                    f"shard world ({self._shard_world}) or retrain the "
                    "optimizer state."
                )
        self.params = state["params"]  # setter re-shards per mode
        rest_sharding = (
            self._replicated if self.broadcast_buffers else self._per_replica
        )
        self.rest = jax.device_put(state["rest"], rest_sharding)
        if self.zero:
            shardings = jax.tree_util.tree_map(
                self.layout.sharding, self._opt_spec,
                is_leaf=lambda x: isinstance(x, P),
            )
            self.opt_state = jax.device_put(state["opt_state"], shardings)
        elif self._ef:
            # the residual is per-replica state: re-place it sharded on
            # the data axis, everything inside it replicated
            inner, ef = state["opt_state"]
            self.opt_state = (
                jax.device_put(inner, self._replicated),
                jax.device_put(ef, self._per_replica),
            )
        else:
            self.opt_state = jax.device_put(
                state["opt_state"], self._replicated
            )


def resume_latest(trainer, directory: str) -> int:
    """Restore ``trainer`` from the newest *verified* checkpoint in
    ``directory`` (manifest-certified; corrupt/truncated candidates are
    skipped by ``utils.checkpoint.load_checkpoint``'s fallback chain).
    Returns the restored step, or 0 when the directory holds no
    checkpoints at all — the "first boot or resume, caller doesn't care
    which" orchestration a preemptible job wants::

        dp = DataParallel(model, opt, loss_fn)
        start = resume_latest(dp, ckpt_dir)   # 0 on first boot
        for step in range(start, total_steps): ...

    Works with any trainer exposing ``state_dict``/``load_state_dict``
    (``DataParallel``, ``GANTrainer``). A directory where every candidate
    fails verification raises ``CheckpointCorruptError`` — that is an
    operator problem, not a fresh start."""
    from tpu_syncbn.utils import checkpoint as ckpt

    try:
        state, step = ckpt.load_checkpoint(directory, trainer.state_dict())
    except FileNotFoundError:
        return 0
    trainer.load_state_dict(state)
    dist.get_logger("tpu_syncbn.resilience").info(
        "resumed from verified checkpoint step %d in %s", step, directory
    )
    return step
