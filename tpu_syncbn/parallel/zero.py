"""ZeRO-style flat parameter layout for sharded optimizer training.

The reference's DDP replicates parameters AND optimizer state on every
rank (``[torch] nn/parallel/distributed.py:466`` — the wrapper holds a
full module copy; the optimizer is a plain local optimizer, recipe
``README.md:62-72``). ZeRO (Rajbhandari et al., 2020) removes that
redundancy by partitioning. This module provides the TPU-native
formulation used by ``DataParallel(zero=True)``:

* parameters live **flat and sharded** across the ``data`` axis between
  steps — one 1-D vector per dtype, padded to a multiple of the world
  size, each device holding a ``1/world`` contiguous shard;
* each step: one ``all_gather`` rebuilds full params (ZeRO-3-style
  storage, whole-model granularity), one ``psum_scatter`` averages AND
  shards the gradients (replacing DDP's all-reduce at identical wire
  cost: reduce-scatter + all-gather = all-reduce), and the optimizer
  updates only the local shard — so optimizer state (e.g. Adam moments,
  2× params in f32) is born sharded and never materializes fully.

The layout is the pure-data part: dtype-grouped flatten/unflatten of an
arbitrary pytree, stable order, jit-safe, with a host-side inverse for
checkpointing. Gradient trees flatten with the SAME layout, which is
what lines the scattered gradient shard up with the parameter shard.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def check_elementwise(optimizer) -> None:
    """Reject optimizers whose update needs a global view across the
    parameter vector (e.g. ``optax.clip_by_global_norm``): under ZeRO each
    device updates only its 1/world shard, so such transforms would
    compute their statistic per-shard and silently diverge from the
    replicated trainer. Probe numerically: one update on a small vector
    must equal the concatenation of shard-wise updates."""
    # Multi-step probe with non-proportional gradients: a single step is
    # not enough (Adam's first update is scale-invariant, so a uniform
    # per-shard clip factor would cancel out and hide the divergence).
    import optax as _optax

    rng = np.random.default_rng(0)
    gs = [
        jnp.asarray(rng.standard_normal(16).astype(np.float32) * (k + 1))
        for k in range(3)
    ]
    vec0 = jnp.asarray(rng.standard_normal(16).astype(np.float32))

    def run(vec, grads):
        state = optimizer.init(vec)
        for g in grads:
            up, state = optimizer.update(g, state, vec)
            vec = _optax.apply_updates(vec, up)
        return np.asarray(vec)

    full = run(vec0, gs)
    parts = [
        run(vec0[i * 4:(i + 1) * 4], [g[i * 4:(i + 1) * 4] for g in gs])
        for i in range(4)
    ]
    if not np.allclose(full, np.concatenate(parts), rtol=1e-5, atol=1e-7):
        raise ValueError(
            "zero=True requires an elementwise optimizer: this optimizer's "
            "update on a vector differs from shard-wise updates (a "
            "global-view transform like clip_by_global_norm?). Under ZeRO "
            "each device sees only its 1/world parameter shard, so such a "
            "transform would silently train differently than zero=False."
        )


def unshard_params(layout: "FlatLayout", store: dict):
    """Gather ZeRO flat parameter shards back into the full pytree — the
    serving-side inverse of the training layout (each device holds a
    1/world contiguous slice of one flat vector per dtype; serving wants
    the whole tree, once, to re-replicate). This is the layout-change
    problem of "Memory-efficient array redistribution through portable
    collective communication" (arxiv 2112.01075) at whole-model
    granularity: one gather per dtype group, then the host-side
    unflatten. ``serve.InferenceEngine.from_trainer`` and
    ``DataParallel.params`` both restore through here.

    This is the *host* path: the full tree materializes in one process
    (pinned as ``max_replicated_bytes`` in the sharding goldens). The
    on-mesh alternative — same layout change, device-to-device
    collectives only, bounded per-device transfer — is
    :func:`tpu_syncbn.parallel.redistribute.portable_redistribute`
    (golden-pinned as the ``serve.redistribute`` audit contract), which
    the zero-downtime publication path
    (:mod:`tpu_syncbn.serve.publish`) uses for live engine swaps."""
    return layout.unflatten_host(store)


class FlatLayout:
    """Dtype-grouped flat layout of a pytree.

    Leaves are grouped by dtype (one flat vector per dtype — mixed
    precision would otherwise force a lossy common cast), concatenated
    in tree-flatten order, and zero-padded so every vector length is a
    multiple of ``world`` (shardable by ``psum_scatter``/``all_gather``).
    """

    def __init__(self, tree, world: int):
        leaves, self.treedef = jax.tree_util.tree_flatten(tree)
        self.world = int(world)
        self.specs = [(str(l.dtype), l.shape, int(np.prod(l.shape, dtype=np.int64)))
                      for l in leaves]
        self.groups: dict[str, list[int]] = {}
        for i, (dt, _, _) in enumerate(self.specs):
            self.groups.setdefault(dt, []).append(i)
        self.padded: dict[str, int] = {}
        for dt, idxs in self.groups.items():
            total = sum(self.specs[i][2] for i in idxs)
            self.padded[dt] = total + (-total) % self.world

    @property
    def shard_sizes(self) -> dict[str, int]:
        return {dt: n // self.world for dt, n in self.padded.items()}

    def flatten(self, tree) -> dict[str, jax.Array]:
        """Pytree -> {dtype: padded 1-D vector}. Jit-safe; also the
        gradient-flattening path (grads share the params' structure)."""
        leaves = jax.tree_util.tree_leaves(tree)
        if len(leaves) != len(self.specs):
            raise ValueError(
                f"tree has {len(leaves)} leaves, layout expects {len(self.specs)}"
            )
        out = {}
        for dt, idxs in self.groups.items():
            parts = [jnp.ravel(leaves[i]) for i in idxs]
            flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
            pad = self.padded[dt] - flat.size
            if pad:
                flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
            out[dt] = flat
        return out

    def unflatten(self, vecs: dict[str, jax.Array]):
        """{dtype: padded 1-D vector} -> pytree. Jit-safe."""
        leaves = [None] * len(self.specs)
        for dt, idxs in self.groups.items():
            vec, off = vecs[dt], 0
            for i in idxs:
                _, shape, size = self.specs[i]
                leaves[i] = jax.lax.dynamic_slice_in_dim(vec, off, size).reshape(shape)
                off += size
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def unflatten_host(self, vecs: dict[str, jax.Array]):
        """Host-side inverse for checkpoint/introspection: accepts the
        sharded storage arrays, gathers them, and rebuilds the tree as
        host-backed jnp arrays. Single-host, ``np.asarray`` assembles
        the global value from local shards; on a multi-process mesh the
        remote shards are non-addressable and must be fetched with a
        cross-host gather instead."""

        def to_host(v):
            if getattr(v, "is_fully_addressable", True):
                return np.asarray(v)
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(v, tiled=True))

        host = {dt: to_host(v) for dt, v in vecs.items()}
        leaves = [None] * len(self.specs)
        for dt, idxs in self.groups.items():
            vec, off = host[dt], 0
            for i in idxs:
                _, shape, size = self.specs[i]
                leaves[i] = jnp.asarray(vec[off:off + size].reshape(shape))
                off += size
        return jax.tree_util.tree_unflatten(self.treedef, leaves)
