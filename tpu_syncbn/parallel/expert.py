"""Expert parallelism: Switch-style mixture-of-experts over an ``expert``
mesh axis.

The reference recipe has no MoE (absent from ``README.md:1-104``, SURVEY
§2's parallelism inventory) — this is the expert-parallel member of the
beyond-reference set (ring/Ulysses sequence parallelism, ZeRO), built on
the same collective layer. The TPU-native shape:

* tokens are sharded across the axis (data-parallel style);
* expert weights are sharded across the SAME axis — device ``i`` owns
  experts ``[i·E_loc, (i+1)·E_loc)`` and only ever materializes those;
* routing is top-1 (Switch) with a per-(expert, source-device) capacity;
  dispatch/combine are one-hot einsums (static shapes, MXU-friendly —
  no gather/scatter, no dynamic shapes under jit);
* two ``all_to_all``s move token slots to their expert's device and
  back — O(capacity) traffic per device, the EP analogue of the
  sequence module's resharding.

Exactness contract: :func:`expert_parallel_moe` over N devices equals
:func:`dense_moe` (full weights, zero collectives) applied per shard —
the all_to_alls relocate compute without changing it. Pinned with
gradients in ``tests/test_expert_parallel.py``.
"""

from __future__ import annotations

import jax
from tpu_syncbn.compat import axis_size as _compat_axis_size
import jax.numpy as jnp
from jax import lax

# canonical home: tpu_syncbn.mesh_axes (srclint hardcoded_mesh_axis)
from tpu_syncbn.mesh_axes import EXPERT_AXIS  # noqa: E402


def switch_route(
    x: jax.Array, router_w: jax.Array, capacity: int
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-1 routing with capacity. ``x``: (T, D); ``router_w``: (D, E).

    Returns ``(dispatch, combine, aux)``:
      dispatch (T, E, C) 0/1 — token t occupies slot c of expert e;
      combine  (T, E, C) f32 — dispatch scaled by the router probability
      (the Switch estimator: output is prob-weighted so the router gets
      gradients); aux — the Switch load-balance loss
      ``E * Σ_e fraction_e · mean_prob_e`` over these tokens.

    Tokens beyond an expert's capacity are dropped (their combine row is
    zero → they pass through as zeros; residual connections restore them
    in a transformer block). Slot assignment is by token order — the
    deterministic tie-break the exactness tests rely on.
    """
    t, _ = x.shape
    e = router_w.shape[-1]
    logits = (x.astype(jnp.float32)) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # (T, E)
    idx = jnp.argmax(probs, axis=-1)  # (T,)
    onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # (T, E)
    # rank of each token within its expert's queue (>= 0 at the chosen
    # expert since the cumsum includes the token itself; -1 elsewhere)
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # (T, E)
    rank = pos.max(axis=-1).astype(jnp.int32)  # (T,)
    # one_hot is all-zeros for rank >= capacity: over-capacity tokens
    # drop out of dispatch with no separate mask needed
    slot = jax.nn.one_hot(rank, capacity, dtype=jnp.float32)
    dispatch = onehot[:, :, None] * slot[:, None, :]  # (T, E, C)
    gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)  # (T, 1)
    combine = dispatch * gate[:, :, None]
    fraction = onehot.mean(axis=0)  # tokens routed to each expert
    mean_prob = probs.mean(axis=0)
    aux = e * jnp.sum(fraction * mean_prob)
    return dispatch, combine, aux


def _expert_mlp(inputs: jax.Array, w_in: jax.Array, w_out: jax.Array):
    """Batched per-expert 2-layer ReLU MLP: (E, C, D) @ (E, D, H) @ (E, H, D)."""
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", inputs, w_in))
    return jnp.einsum("ech,ehd->ecd", h, w_out)


def _capacity(t: int, e: int, capacity_factor: float) -> int:
    return max(1, int(-(-t * capacity_factor // e)))  # ceil


def dense_moe(
    x: jax.Array,
    router_w: jax.Array,
    w_in: jax.Array,
    w_out: jax.Array,
    *,
    capacity_factor: float = 1.25,
) -> tuple[jax.Array, jax.Array]:
    """Single-device MoE: full expert weights, zero collectives. The n=1
    path and the exactness oracle for the expert-parallel version.
    Returns ``(y, aux)`` with ``y`` shaped like ``x``."""
    t = x.shape[0]
    e = router_w.shape[-1]
    c = _capacity(t, e, capacity_factor)
    dispatch, combine, aux = switch_route(x, router_w, c)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    expert_out = _expert_mlp(expert_in, w_in, w_out)
    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y.astype(x.dtype), aux


def expert_parallel_moe(
    x: jax.Array,
    router_w: jax.Array,
    w_in: jax.Array,
    w_out: jax.Array,
    axis_name: str = EXPERT_AXIS,
    *,
    capacity_factor: float = 1.25,
) -> tuple[jax.Array, jax.Array]:
    """Shard-level expert-parallel MoE (call inside ``shard_map``).

    ``x``: this device's tokens (T_local, D); ``router_w``: replicated
    (D, E_total); ``w_in``/``w_out``: this device's expert shard
    (E_local, D, H) / (E_local, H, D) with ``E_total = E_local · world``.

    Flow: route locally against all experts → dispatch into per-expert
    capacity slots → ``all_to_all`` sends each expert's slots to its
    owning device → batched expert MLP over the local experts → inverse
    ``all_to_all`` → combine. Per-source capacity makes the result
    exactly :func:`dense_moe` per shard. Returns ``(y_local, aux)`` with
    aux ``pmean``'d across the axis.
    """
    n = _compat_axis_size(axis_name)
    t, d = x.shape
    e_local = w_in.shape[0]
    e = router_w.shape[-1]
    if e != e_local * n:
        raise ValueError(
            f"router has {e} experts but shard has {e_local} × world {n}"
        )
    c = _capacity(t, e, capacity_factor)
    dispatch, combine, aux = switch_route(x, router_w, c)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))

    if n == 1:
        expert_out = _expert_mlp(expert_in, w_in, w_out)
    else:
        # (E, C, D) -> (world, E_local, C, D): send slots to expert owners;
        # received leading axis = source device
        grouped = expert_in.reshape(n, e_local, c, d)
        inbound = lax.all_to_all(
            grouped, axis_name, split_axis=0, concat_axis=0, tiled=False
        )  # (world_src, E_local, C, D)
        flat_in = jnp.moveaxis(inbound, 0, 1).reshape(e_local, n * c, d)
        flat_out = _expert_mlp(flat_in, w_in, w_out)
        outbound = jnp.moveaxis(
            flat_out.reshape(e_local, n, c, d), 1, 0
        )  # (world_src, E_local, C, D)
        returned = lax.all_to_all(
            outbound, axis_name, split_axis=0, concat_axis=0, tiled=False
        )  # (world_expert_owner, E_local, C, D)
        expert_out = returned.reshape(e, c, d)

    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y.astype(x.dtype), lax.pmean(aux, axis_name)
