"""Collective communication over mesh axes — the TPU-native replacement for
the reference stack's NCCL process-group layer.

The reference recipe's collectives (reference ``README.md:29-35`` selects the
``'nccl'`` backend; the ops its stack actually issues are pinned in SURVEY §5.8):

* ``all_gather(_single)`` — SyncBN forward stats exchange
  (``[torch] nn/modules/_functions.py:74-86``)
* ``all_reduce(SUM)`` — SyncBN backward (``:160-165``) + DDP gradient buckets
* ``broadcast`` — DDP init-time parameter sync
  (``[torch] nn/parallel/distributed.py:1066-1072``)

Here each op is a thin wrapper over ``jax.lax`` named-axis collectives, legal
inside any ``shard_map``/``pmap``-traced function over a mesh axis. XLA lowers
them to AllReduce/AllGather/CollectivePermute HLOs scheduled over ICI/DCN —
compiler-scheduled rather than runtime-issued, which subsumes NCCL stream
management and DDP's bucketing/overlap machinery (the latency-hiding
scheduler overlaps them with compute automatically).

Also hosts :func:`reduce_moments` — the count-weighted cross-replica moment
reduction that is the numerical core of SyncBatchNorm (the TPU-native
equivalent of ``batch_norm_gather_stats_with_counts``,
``[torch] nn/modules/_functions.py:106-115``): replicas contribute
(sum, sumsq, count) and receive exact global (mean, biased var, count),
correct for uneven/empty shards.
"""

from __future__ import annotations

import math
import operator
import threading
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpu_syncbn.compat import axis_size as _compat_axis_size
from tpu_syncbn.obs import numerics as obs_numerics, telemetry
from tpu_syncbn.runtime.distributed import DATA_AXIS

Pytree = Any

#: Running total of trace-time collective payload bytes (every _tally
#: adds here alongside the per-op counters) — the O(1) read that lets
#: DispatchWireTally run on the step loop without snapshotting the
#: registry per dispatch.
_traced_bytes_lock = threading.Lock()
_traced_bytes_total = 0


def traced_bytes_total() -> int:
    """Trace-time collective bytes tallied so far in this process."""
    with _traced_bytes_lock:
        return _traced_bytes_total


def _tally(op: str, tree: Pytree) -> None:
    """Per-op call + estimated-byte counters (``collectives.<op>.calls``
    / ``.bytes``) when telemetry is enabled.

    These count at **trace time**: collectives in this module execute
    while XLA traces the step program, once per compilation, not once
    per step — so the tallies are the per-program collective inventory
    (DS-Sync's "how much does this step synchronize", arxiv 2007.03298).
    Per-execution traffic is this estimate times the step count; the
    payload estimate is the mathematical per-replica input size
    (shape × itemsize), which for an all-reduce equals what ring
    algorithms move within a factor of 2(N-1)/N.

    Tally at the TRANSMISSION site with the array that actually moves:
    byte counts are shape × itemsize of the tallied leaves, so a helper
    that re-packs its input before the wire (``psum_in_groups`` fusing a
    bf16 tree into one f32 payload, the quantized paths below sending
    int8) must tally the packed/quantized payload, not its logical
    input — otherwise the inventory reports the logical itemsize while
    the wire carries a different one."""
    if not telemetry.enabled():
        return
    nbytes = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        try:
            shape = tuple(getattr(leaf, "shape", ()))
            dtype = getattr(leaf, "dtype", None)
            itemsize = np.dtype(dtype).itemsize if dtype is not None else 0
            nbytes += int(math.prod(shape)) * itemsize
        except (TypeError, ValueError):
            continue  # abstract/dynamic leaf: skip, keep the call count
    telemetry.count(f"collectives.{op}.calls")
    telemetry.count(f"collectives.{op}.bytes", nbytes)
    # O(1) running total for DispatchWireTally — reading it per dispatch
    # must not pay a full registry snapshot on the step loop's hot path
    global _traced_bytes_total
    with _traced_bytes_lock:
        _traced_bytes_total += nbytes


def axis_size(axis_name: str = DATA_AXIS) -> int:
    """World size along a mesh axis — the reference's ``world_size``
    (``README.md:33``), available inside the compiled step."""
    return _compat_axis_size(axis_name)


def axis_index(axis_name: str = DATA_AXIS) -> jax.Array:
    """This replica's index along a mesh axis — the reference's ``rank``
    (``README.md:34``), as a traced scalar."""
    return lax.axis_index(axis_name)


def psum(tree: Pytree, axis_name: str = DATA_AXIS) -> Pytree:
    """Sum every leaf across the axis: ``dist.all_reduce(SUM)``
    (as used by SyncBN backward, ``[torch] nn/modules/_functions.py:160-165``)."""
    _tally("psum", tree)
    return lax.psum(tree, axis_name)


def pmean(tree: Pytree, axis_name: str = DATA_AXIS) -> Pytree:
    """Mean every leaf across the axis — all_reduce followed by the divide
    DDP's reducer applies to gradients (``[torch] nn/parallel/distributed.py``
    Reducer grad averaging)."""
    _tally("pmean", tree)
    return lax.pmean(tree, axis_name)


def pmax(tree: Pytree, axis_name: str = DATA_AXIS) -> Pytree:
    """Elementwise max across the axis (all_reduce(MAX))."""
    _tally("pmax", tree)
    return lax.pmax(tree, axis_name)


def pmin(tree: Pytree, axis_name: str = DATA_AXIS) -> Pytree:
    """Elementwise min across the axis (all_reduce(MIN))."""
    _tally("pmin", tree)
    return lax.pmin(tree, axis_name)


def all_gather(
    tree: Pytree,
    axis_name: str = DATA_AXIS,
    *,
    axis: int = 0,
    tiled: bool = False,
) -> Pytree:
    """Gather every replica's leaf along a new (or tiled) leading axis:
    ``dist.all_gather_into_tensor`` (SyncBN forward stats exchange,
    ``[torch] nn/modules/_functions.py:74-77``)."""
    _tally("all_gather", tree)
    return lax.all_gather(tree, axis_name, axis=axis, tiled=tiled)


def broadcast(tree: Pytree, src: int = 0, axis_name: str = DATA_AXIS) -> Pytree:
    """Every replica receives replica ``src``'s value: ``dist.broadcast``
    (DDP init-time param/buffer sync from rank 0,
    ``[torch] nn/parallel/distributed.py:1066-1072``).

    SPMD formulation: gather all replicas' values and select ``src``'s.
    XLA folds the gather+index; for the init-time use the cost is a one-off.

    ``axis_name`` may be a tuple of mesh axes (a composed layout such as
    ``('data', 'fsdp')``): ``src`` is then a linear rank decomposed
    row-major over the axes in the order given, and the masked psum runs
    over all of them at once.
    """
    _tally("broadcast", tree)
    size = int(_compat_axis_size(axis_name))  # static at trace time
    if not -size <= src < size:
        raise ValueError(
            f"broadcast src={src} out of range for axis {axis_name!r} of size {size}"
        )
    src = src % size
    # psum of the masked value: no world_size× gather buffer, one AllReduce.
    if isinstance(axis_name, (tuple, list)):
        axes = tuple(axis_name)
        sizes = [int(_compat_axis_size(a)) for a in axes]
        coords, rem = [], src
        for n in reversed(sizes):
            coords.append(rem % n)
            rem //= n
        coords.reverse()
        is_src = jnp.bool_(True)
        for a, c in zip(axes, coords):
            is_src = jnp.logical_and(is_src, lax.axis_index(a) == c)
        psum_axes: object = axes
    else:
        is_src = lax.axis_index(axis_name) == src
        psum_axes = axis_name

    def one(x):
        return lax.psum(jnp.where(is_src, x, jnp.zeros_like(x)), psum_axes)

    return jax.tree_util.tree_map(one, tree)


def pcast_varying(tree: Pytree, axis_name: str = DATA_AXIS) -> Pytree:
    """Idempotently cast every leaf to device-varying over ``axis_name``
    (``lax.pcast`` raises on an already-varying input, and mixed trees are
    common: SyncBN stats come out of their psum unvarying while plain-BN
    stats stay varying). Shared home for the VMA-cast used by the
    trainers and the sequence-parallel scan carries — one place to adapt
    if jax's vma/pcast API shifts again."""

    from tpu_syncbn import compat

    if not compat.HAS_VMA:
        return tree  # pre-VMA jax: no varying type to cast to

    axes = tuple(axis_name) if isinstance(axis_name, (tuple, list)) else (axis_name,)

    def leaf(x):
        for a in axes:
            if a not in getattr(jax.typeof(x), "vma", frozenset()):
                x = lax.pcast(x, a, to="varying")
        return x

    return jax.tree_util.tree_map(leaf, tree)


def ppermute(
    tree: Pytree, perm: list[tuple[int, int]], axis_name: str = DATA_AXIS
) -> Pytree:
    """Point-to-point ring/permutation sends (CollectivePermute over ICI).
    No reference analogue in the recipe; exposed for ring-style algorithms."""
    _tally("ppermute", tree)
    return lax.ppermute(tree, axis_name, perm)


def all_to_all(
    tree: Pytree,
    axis_name: str = DATA_AXIS,
    *,
    split_axis: int = 0,
    concat_axis: int = 0,
    tiled: bool = True,
) -> Pytree:
    """All-to-all resharding (sequence/expert-parallel building block).
    Not used by the reference recipe; exposed as the mesh-ready extension
    point SURVEY §2 calls for."""
    _tally("all_to_all", tree)
    return lax.all_to_all(
        tree, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def reduce_scatter(
    x: jax.Array, axis_name: str = DATA_AXIS, *, scatter_dimension: int = 0
) -> jax.Array:
    """Sum across the axis, then shard the result along ``scatter_dimension``
    (ReduceScatter HLO). The building block for ZeRO-style sharded optimizer
    states (out of reference scope, SURVEY §2, but mesh-ready)."""
    _tally("reduce_scatter", x)
    return lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=True
    )


def _prime_factors(n: int) -> list:
    """Ascending prime factorization (with multiplicity); empty for 1."""
    fs, f = [], 2
    while n > 1:
        while n % f == 0:
            fs.append(f)
            n //= f
        f += 1 if f == 2 else 2
    return fs


def _stage_perm(
    groups: tuple, stride: int, f: int, k: int
) -> list:
    """(source, dest) ppermute pairs for shift ``k`` of a radix-``f``
    mixed-radix butterfly stage at ``stride``, within equal-size replica
    ``groups`` (arbitrary membership): each member receives from the
    group member whose position digit at this stride is ``k`` ahead
    (mod f). Contiguous groups are the special case
    ``groups[i] = range(i*g, (i+1)*g)``."""
    perm = []
    for g in groups:
        for pos, rank in enumerate(g):
            d = (pos // stride) % f
            src_pos = pos + (((d + k) % f) - d) * stride
            perm.append((g[src_pos], rank))
    return perm


def normalize_group_spec(group_size):
    """Canonicalize a ``group_size`` value: an int-like scalar stays an
    int (contiguous groups of that size); anything else must be a rank
    partition and becomes hashable nested tuples of exact ints
    (``operator.index`` — a non-integral rank like 1.9 is an error, not
    a silent truncation). ONE normalization shared by ``SyncBatchNorm``,
    ``convert_sync_batchnorm`` and ``psum_in_groups`` so the value
    hashes/compares identically across jit cache keys. ``None`` passes
    through (full-world sync)."""
    if group_size is None:
        return None
    if isinstance(group_size, bool):
        raise ValueError(f"group_size must be an int or a rank "
                         f"partition, got {group_size!r}")
    try:
        return operator.index(group_size)  # int, np.integer, ...
    except TypeError:
        pass
    try:
        return tuple(tuple(operator.index(r) for r in g)
                     for g in group_size)
    except (TypeError, ValueError) as e:
        raise ValueError(
            "group_size must be an int or a sequence of rank "
            f"sequences of exact integers, got {group_size!r}"
        ) from e


def _validate_partition(world: int, groups: tuple) -> tuple:
    """Check a normalized rank partition: every rank in [0, world)
    exactly once, no empty groups. Returns it unchanged."""
    flat = [r for g in groups for r in g]
    if any(not g for g in groups) or sorted(flat) != list(range(world)):
        raise ValueError(
            f"groups {groups!r} must partition ranks 0..{world - 1}: "
            "every rank exactly once, no empty groups (torch builds its "
            "process groups under the same constraint — "
            "[torch] distributed/distributed_c10d.py new_group)"
        )
    return groups


def psum_in_groups(
    tree: Pytree, axis_name: str, group_size
) -> Pytree:
    """Sum within replica subgroups along the axis — the TPU form of
    torch's ``process_group`` scoping (e.g. SyncBN synced within a node
    rather than the whole world).

    ``group_size`` is either

    * an ``int`` g: contiguous groups ``[0..g), [g..2g), ...`` (g must
      divide the axis size) — the common topology-shaped case, or
    * an explicit partition — a sequence of rank sequences covering
      every rank exactly once, e.g. ``((0, 3, 5, 6), (1, 2, 4, 7))`` —
      matching the arbitrary rank sets torch's ``process_group``
      accepts (``[torch] nn/modules/batchnorm.py:706``).

    ``lax.psum(axis_index_groups=...)`` is unimplemented under shard_map's
    VMA checker (jax 0.9: the type system cannot express a group-varying
    reduce result), so equal-size groups take a **mixed-radix butterfly**
    of ``ppermute``s: the group size is factorized and each prime factor
    ``f`` contributes one stage of ``f - 1`` shifted exchanges —
    O(payload · Σ(fᵢ − 1)) traffic for ANY group size (log₂ g messages
    when g is a power of two, where radix-2 stages reduce to the classic
    recursive-doubling XOR butterfly), never an O(world) gather. All
    perms are compile-time constants, VMA-legal CollectivePermute HLOs;
    for contiguous groups XLA schedules them over the direct ICI
    neighbor links the groups sit on (arbitrary-membership groups keep
    the same message count but may route across the mesh). The whole
    tree moves as ONE fused payload, keeping the "one collective per BN
    layer" property.

    Unequal-size groups cannot share one butterfly schedule (stage
    counts differ per group), so they fall back to a masked all-gather:
    one AllGather of the fused payload plus a per-replica constant
    membership row — O(world · payload) traffic, the same order as the
    reference's SyncBN stats exchange (``all_gather`` of every rank's
    stats, ``[torch] nn/modules/_functions.py:74-86``), so the fallback
    is never worse than the semantics it emulates.

    Latency note: a large *prime* factor f contributes f-1 dependent
    exchange rounds (ring-like latency), so e.g. g=13 pays 12 round
    trips where a gather would pay one. Real stat-sync groups are
    topology-shaped (2/4/8 replicas per host, occasionally 3/6), where
    Σ(fᵢ−1) ≤ 4 — the design targets those; for exotic large-prime
    groups prefer ``group_size=None`` (full-world psum) or an explicit
    unequal partition (which takes the gather path).
    """
    world = _compat_axis_size(axis_name)
    group_size = normalize_group_spec(group_size)
    if isinstance(group_size, int):
        if group_size < 1 or world % group_size:
            raise ValueError(
                f"group_size {group_size} must divide axis size {world}"
            )
        if group_size == world:
            return lax.psum(tree, axis_name)
        groups = tuple(
            tuple(range(i, i + group_size))
            for i in range(0, world, group_size)
        )
    else:
        groups = _validate_partition(world, group_size)
        if len(groups) == 1:
            return lax.psum(tree, axis_name)

    # one fused payload for the whole tree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    flat = jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for l in leaves])

    sizes = {len(g) for g in groups}
    if len(sizes) == 1:
        stride = 1
        for f in _prime_factors(sizes.pop()):
            # radix-f stage: each member sums the f values whose
            # mixed-radix position digit at this stride differs — after
            # the stage, every member holds the sum over its digit
            # group; after all stages, the full group sum
            acc = flat
            for k in range(1, f):
                perm = _stage_perm(groups, stride, f, k)
                # wire payload is the fused f32 vector, NOT the caller's
                # tree — tally what each exchange actually transmits
                _tally("ppermute", flat)
                acc = acc + lax.ppermute(flat, axis_name, perm)
            flat = acc
            stride *= f
        summed = flat
    else:
        # masked gather: every replica sees every row, sums its group's
        _tally("all_gather", flat)  # wire dtype: the fused f32 payload
        gathered = lax.all_gather(flat, axis_name)  # (world, payload)
        member = [[0.0] * world for _ in range(world)]
        for g in groups:
            for i in g:
                for j in g:
                    member[i][j] = 1.0
        row = jnp.take(
            jnp.asarray(member, jnp.float32),
            lax.axis_index(axis_name), axis=0,
        )
        # elementwise mask + sum, NOT a matmul: jnp.matmul at default
        # precision runs bf16 multiply passes on TPU, which would break
        # the f32 accumulation the payload was cast to float32 for
        summed = (row[:, None] * gathered).sum(0)

    out = []
    offset = 0
    for l in leaves:
        n = l.size
        out.append(summed[offset : offset + n].reshape(l.shape).astype(l.dtype))
        offset += n
    return jax.tree_util.tree_unflatten(treedef, out)


def ring_all_reduce(
    x: jax.Array, axis_name: str = DATA_AXIS
) -> jax.Array:
    """Bandwidth-optimal ring all-reduce built from ``ppermute`` steps —
    the explicit form of what NCCL's ring kernels (reference ``'nccl'``
    backend, ``README.md:31``) and XLA's AllReduce do internally.

    reduce-scatter phase: N-1 neighbor hops, each accumulating one 1/N
    chunk; all-gather phase: N-1 hops circulating the finished chunks.
    Total traffic per device: 2·(N-1)/N · payload — the ring optimum.

    ``lax.psum`` (one AllReduce HLO that XLA schedules over ICI) is the
    production path; this exists to (a) pin the ring algebra with tests,
    (b) serve as the template for ring-style long-context algorithms
    (ring attention passes KV blocks around the same neighbor cycle
    while overlapping compute — SURVEY §5.7's extension point).
    """
    n = _compat_axis_size(axis_name)
    if n == 1:
        return x
    orig_shape = x.shape
    flat = jnp.ravel(x)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    chunks = flat.reshape(n, -1)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    me = lax.axis_index(axis_name)

    # reduce-scatter: at step s device ``me`` receives the partial sum of
    # chunk (me - s) from its left neighbor and adds its own copy; after
    # N-1 steps it owns the complete sum of chunk (me + 1) % n
    acc = jnp.take(chunks, me, axis=0)
    for s in range(1, n):
        _tally("ppermute", acc)  # each hop moves one 1/N chunk
        acc = lax.ppermute(acc, axis_name, fwd)
        acc = acc + jnp.take(chunks, (me - s) % n, axis=0)
    # all-gather: circulate each finished chunk around the ring
    gathered = [acc]
    cur = acc
    for _ in range(n - 1):
        _tally("ppermute", cur)
        cur = lax.ppermute(cur, axis_name, fwd)
        gathered.append(cur)
    # device me received chunk (me - s + 1) % n at gather step s; restore
    # index order: out[j] = gathered[(me + 1 - j) % n]
    order = jnp.stack(gathered)  # (n, chunk)
    idx = (me + 1 - jnp.arange(n)) % n
    out = jnp.take(order, idx, axis=0).reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(orig_shape)


def reduce_moments(
    local_sum: jax.Array,
    local_sumsq: jax.Array,
    local_count: jax.Array,
    axis_name: str = DATA_AXIS,
    *,
    group_size: int | tuple | None = None,
    mode: str = "none",
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Count-weighted global moments from per-replica partial sums.

    The numerical heart of SyncBatchNorm. The reference all_gathers per-rank
    ``[mean, invstd, count]`` and recombines with
    ``batch_norm_gather_stats_with_counts``
    (``[torch] nn/modules/_functions.py:41-115``) precisely because shards
    may be uneven or empty (``:50-57``). Summing raw (sum, sumsq, count)
    with a single fused ``psum`` is algebraically identical, needs one
    collective instead of an all_gather + recombine, and is exact for
    empty shards (they contribute zeros, matching ``:195-205``).

    ``mode`` (default ``"none"`` — stats stay exact fp32) opts the
    (sum, sumsq) payload into a lossy wire dtype via
    :func:`compressed_psum`; the **count always rides fp32** — it feeds
    the safe-divide and the empty-shard semantics, and quantizing an
    integer census would corrupt uneven-shard correctness for a handful
    of saved bytes. Lossy stats cannot be scoped to subgroups
    (``group_size``): the butterfly path re-fuses payloads at f32, so
    combining the two flags raises instead of silently un-compressing.

    Args:
      local_sum:   per-channel sum of x over this replica's local elements.
      local_sumsq: per-channel sum of x² over this replica's local elements.
      local_count: scalar (or per-channel) number of local elements.

    Returns:
      (global_mean, global_biased_var, global_count). Variance is the
      *biased* (1/N) variance — what BN normalizes with; the unbiased
      running-var correction is the caller's job (see ops.batch_norm).
    """
    check_compress_mode(mode)
    triple = (local_sum, local_sumsq, local_count)
    if group_size is not None:
        if isinstance(axis_name, (tuple, list)):
            raise ValueError(
                "group-scoped SyncBN stats need a single stat axis — the "
                "butterfly group reduction is 1-D; a composed layout "
                f"syncs over {tuple(axis_name)}"
            )
        if mode != "none":
            raise ValueError(
                "compressed SyncBN stats (mode="
                f"{mode!r}) cannot be combined with group_size="
                f"{group_size!r}: the group butterfly re-fuses payloads "
                "at f32 — sync the full axis or keep stats exact"
            )
        total_sum, total_sumsq, total_count = psum_in_groups(
            triple, axis_name, group_size
        )
    elif mode != "none":
        total_sum, total_sumsq = compressed_psum(
            (local_sum, local_sumsq), axis_name, mode=mode
        )
        total_count = psum(local_count, axis_name)
    else:
        total_sum, total_sumsq, total_count = psum(triple, axis_name)
    mean, var = moments_from_stats(total_sum, total_sumsq, total_count)
    # numerics drift monitor (ISSUE 13): this replica's batch moments vs
    # the just-synced global ones — local arithmetic after the existing
    # psum, traced only while a trainer's monitor collector is active
    obs_numerics.record_bn_skew(
        local_sum, local_sumsq, local_count, mean, var
    )
    return mean, var, total_count


def moments_from_stats(
    s: jax.Array, sq: jax.Array, count: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """(mean, biased var) from raw partial sums; safe for count==0, and
    clamps the tiny negative values that cancellation in ``sumsq - n·mean²``
    can produce. Single home for this math — both the local path
    (ops.batch_norm) and the cross-replica path above use it."""
    safe = jnp.maximum(count, 1.0)
    mean = s / safe
    var = jnp.maximum(sq / safe - mean * mean, 0.0)
    return mean, var


# ---------------------------------------------------------------------------
# compressed collectives (EQuARX-style quantized all-reduce, arxiv
# 2506.17615; DS-Sync shuffle-sharding, arxiv 2007.03298)

#: Wire-compression modes accepted by every ``compressed_*`` entry point
#: (and the trainers' ``compress=``): ``"none"`` exact fp32, ``"bf16"``
#: dtype-cast (2 B/elem), ``"int8"`` chunk-quantized (1 B/elem + one
#: fp32 scale/zero-point pair per chunk).
COMPRESS_MODES = ("none", "bf16", "int8")

#: Elements per quantization chunk: one (scale, zero-point) pair is
#: shared by this many consecutive elements of the fused payload. 256
#: keeps the fp32 side-channel at 8/256 ≈ 3% of the int8 payload while
#: bounding the blast radius of one outlier element to its own chunk.
DEFAULT_CHUNK_ELEMS = 256


def check_compress_mode(mode: str) -> str:
    if mode not in COMPRESS_MODES:
        raise ValueError(
            f"compression mode must be one of {COMPRESS_MODES}, got {mode!r}"
        )
    return mode


def _tally_compressed(logical_bytes: int, wire_bytes: int) -> None:
    """Trace-time compression accounting (docs/OBSERVABILITY.md):
    ``collectives.compressed_bytes`` counts what the lossy payloads put
    on the wire; the gauge holds logical/wire for the most recent
    compressed collective. The underlying psum/pmax calls tally their
    own per-op bytes at the wire dtype as usual."""
    if not telemetry.enabled():
        return
    telemetry.count("collectives.compressed_bytes", int(wire_bytes))
    telemetry.count(
        "collectives.compressed_saved_bytes",
        max(0, int(logical_bytes) - int(wire_bytes)),
    )
    if wire_bytes:
        telemetry.set_gauge(
            "collectives.compression_ratio", logical_bytes / wire_bytes
        )


def _nbytes(leaves) -> int:
    return sum(
        int(math.prod(tuple(l.shape))) * np.dtype(l.dtype).itemsize
        for l in leaves
    )


def _split_float_leaves(tree: Pytree):
    """(treedef, float-leaf list, float index list, all leaves): the
    compressed paths quantize floating leaves and move anything else
    (int flags, counters) through an exact psum."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    fidx = [i for i, l in enumerate(leaves)
            if jnp.issubdtype(jnp.dtype(l.dtype), jnp.floating)]
    return treedef, [leaves[i] for i in fidx], fidx, leaves


def _fuse_f32(leaves) -> jax.Array:
    """Fuse leaves into ONE flat f32 payload (quantization chunks then
    span leaf boundaries — per-chunk ranges stay local to 256 elements
    regardless of layer shapes)."""
    parts = [jnp.ravel(l).astype(jnp.float32) for l in leaves]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def _unfuse(flat: jax.Array, like_leaves, *, cast: bool = True):
    out, offset = [], 0
    for l in like_leaves:
        n = int(math.prod(tuple(l.shape)))
        piece = flat[offset:offset + n].reshape(tuple(l.shape))
        out.append(piece.astype(l.dtype) if cast else piece)
        offset += n
    return out


def _reassemble(treedef, leaves, fidx, freduced, exact):
    """Re-interleave the compressed-reduced float leaves and the
    exactly-reduced non-float leaves back into the original tree order —
    ONE implementation shared by :func:`compressed_psum` and
    :func:`ef_compressed_pmean` so the interleave can't drift between
    them."""
    out = list(leaves)
    fset = set(fidx)
    for i, s in zip(fidx, freduced):
        out[i] = s
    it = iter(exact)
    for i in range(len(out)):
        if i not in fset:
            out[i] = next(it)
    return jax.tree_util.tree_unflatten(treedef, out)


def _int8_qparams(
    blocks: jax.Array, axis_name: str, world: int
) -> tuple[jax.Array, jax.Array, jax.Array, int]:
    """Shared-range asymmetric int8 quantization parameters for chunked
    ``blocks`` (n_chunks, chunk).

    The range is the WORLD range (one tiny fp32 ``pmax`` of the per-chunk
    (-min, max) pairs), so every replica quantizes on the same grid and
    the int8 payloads sum EXACTLY on the wire: per-element magnitudes
    are budgeted to ``qmax = 127 // world``, hence a world-sum of
    ``world × qmax ≤ 127`` — no overflow, and ``psum`` of the int8
    payload is a legal s8 AllReduce whose result is bit-defined. The
    log2(world) bits the budget costs are exactly what error feedback
    (:func:`ef_compressed_pmean`) recovers across steps.

    The budget vanishes at ``world > 127`` (``127 // world == 0``), so
    int8 mode refuses such axes instead of letting a floored qmax wrap
    the s8 accumulator — use ``"bf16"`` there, or reduce hierarchically
    in subgroups."""
    if world > 127:
        raise ValueError(
            f"int8 compression supports axis sizes up to 127, got "
            f"{world}: the no-overflow element budget 127 // world is "
            "zero, so world-sums would wrap int8 — use mode='bf16'"
        )
    n = blocks.shape[0]
    lmin = blocks.min(axis=1)
    lmax = blocks.max(axis=1)
    stats = pmax(jnp.concatenate([-lmin, lmax]), axis_name)
    gmin, gmax = -stats[:n], stats[n:]
    zp = ((gmax + gmin) * 0.5)[:, None]
    half = ((gmax - gmin) * 0.5)[:, None]
    qmax = 127 // world
    scale = jnp.where(half > 0, half / qmax, 1.0)
    q = jnp.clip(
        jnp.round((blocks - zp) / scale), -qmax, qmax
    ).astype(jnp.int8)
    if obs_numerics.active():
        # compression-health monitor (ISSUE 13): fraction of elements
        # sitting at the clip boundary ±qmax — a chunk whose mass pins
        # the shared range edge is saturating, not quantizing. Traced
        # only under an active monitor collector (local arithmetic).
        at_limit = (jnp.abs(q.astype(jnp.int32)) >= qmax)
        obs_numerics.record(
            "clip_fraction", jnp.mean(at_limit.astype(jnp.float32))
        )
    return q, scale, zp, qmax


def _record_int8_headroom(sumq: jax.Array) -> None:
    """Compression-health monitor (ISSUE 13): shared-range overflow
    headroom of a world-summed int8 payload — 1 − max|Σq|/127. The
    ``127 // world`` element budget guarantees this stays ≥ 0; a value
    approaching 0 means the budget is fully consumed and any future
    world growth would wrap the s8 accumulator. Local arithmetic on the
    already-reduced payload; traced only under an active collector."""
    if obs_numerics.active():
        obs_numerics.record(
            "overflow_headroom",
            1.0 - jnp.max(jnp.abs(sumq.astype(jnp.float32))) / 127.0,
        )


def _chunk_pad(flat: jax.Array, chunk: int) -> jax.Array:
    pad = (-flat.size) % chunk
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def compressed_psum(
    tree: Pytree,
    axis_name: str = DATA_AXIS,
    *,
    mode: str,
    chunk_size: int = DEFAULT_CHUNK_ELEMS,
) -> Pytree:
    """All-reduce with a compressed wire dtype — everything happens
    inside the compiled step, so XLA schedules one quantize → AllReduce
    → dequantize chain with no host involvement (EQuARX's framing:
    compression as part of the collective, arxiv 2506.17615).

    * ``"none"``  — plain exact :func:`psum` (one code path for callers).
    * ``"bf16"``  — leaves cast to bfloat16 for the wire, summed in
      bf16, cast back: 2× fewer bytes, exact when the addends and sums
      are bf16-representable.
    * ``"int8"``  — float leaves fused into one flat payload, chunk-wise
      asymmetric quantization (shared world range per chunk via one tiny
      fp32 ``pmax``; see :func:`_int8_qparams` for the overflow budget),
      s8 AllReduce, dequantize: ~4× fewer bytes.

    Non-float leaves (counts, flags) always ride an exact psum. Lossy
    modes are *opt-in by signature* — there is no lossy default anywhere
    in the package (the ``lossy_default_mode`` lint rule pins that), and
    the divergence guard's pmin/finiteness collectives never route
    through here."""
    check_compress_mode(mode)
    if mode == "none":
        return psum(tree, axis_name)
    treedef, fleaves, fidx, leaves = _split_float_leaves(tree)
    if not fleaves:
        return psum(tree, axis_name)
    world = _compat_axis_size(axis_name)
    logical = _nbytes(fleaves)
    exact = [l for i, l in enumerate(leaves) if i not in set(fidx)]
    if exact:
        exact = psum(exact, axis_name)
    if mode == "bf16":
        cast = [l.astype(jnp.bfloat16) for l in fleaves]
        _tally_compressed(logical, _nbytes(cast))
        summed = psum(cast, axis_name)
        fsummed = [s.astype(l.dtype) for s, l in zip(summed, fleaves)]
    else:  # int8
        flat = _chunk_pad(_fuse_f32(fleaves), chunk_size)
        blocks = flat.reshape(-1, chunk_size)
        q, scale, zp, _ = _int8_qparams(blocks, axis_name, world)
        # wire = s8 payload + the fp32 (-min, max) pair per chunk the
        # range pmax moves (8 B/chunk) — matches the traced contract
        _tally_compressed(logical, q.size + 8 * q.shape[0])
        sumq = psum(q, axis_name)
        _record_int8_headroom(sumq)
        summed_flat = (
            scale * sumq.astype(jnp.float32) + world * zp
        ).reshape(-1)
        fsummed = _unfuse(summed_flat, fleaves)
    return _reassemble(treedef, leaves, fidx, fsummed, exact)


def compressed_pmean(
    tree: Pytree,
    axis_name: str = DATA_AXIS,
    *,
    mode: str,
    chunk_size: int = DEFAULT_CHUNK_ELEMS,
) -> Pytree:
    """:func:`compressed_psum` followed by the world-size divide — the
    compressed form of DDP's gradient averaging. The divide happens
    post-dequantize in the leaf dtype (for ``world`` a power of two it
    is exact, so the bf16 parity pin holds through the mean)."""
    world = _compat_axis_size(axis_name)
    summed = compressed_psum(
        tree, axis_name, mode=mode, chunk_size=chunk_size
    )
    # plain division, exactly like lax.pmean: float leaves keep their
    # dtype (a weak-typed divisor), integer leaves promote to the float
    # mean — casting back to int would silently truncate counts
    return jax.tree_util.tree_map(lambda s: s / world, summed)


def init_error_feedback(tree: Pytree) -> Pytree:
    """Zero residual matching ``tree``'s float leaves (f32, same shapes;
    non-float leaves carry a zero-size placeholder so the residual tree
    keeps the gradient tree's structure)."""
    def zero(l):
        if jnp.issubdtype(jnp.dtype(l.dtype), jnp.floating):
            return jnp.zeros(tuple(l.shape), jnp.float32)
        return jnp.zeros((0,), jnp.float32)
    return jax.tree_util.tree_map(zero, tree)


def ef_compressed_pmean(
    tree: Pytree,
    residual: Pytree,
    axis_name: str = DATA_AXIS,
    *,
    mode: str,
    chunk_size: int = DEFAULT_CHUNK_ELEMS,
) -> tuple[Pytree, Pytree]:
    """Error-feedback compressed gradient mean (EF-SGD / 1-bit-Adam
    lineage): each replica reduces ``p = g + e`` instead of ``g`` and
    re-captures ``e' = p − C(p)`` — its own quantization error — so
    compression error does NOT accumulate across steps (it is re-sent
    until it lands). Returns ``(mean over replicas of C(p), e')``.

    ``residual`` is per-replica state (every replica's error differs);
    the trainers store it inside ``opt_state`` exactly like the PR 1
    divergence-guard state, so it persists through checkpoints, rides
    fused-scan carries, and is rolled back with everything else on a
    guarded non-finite step. ``mode="none"`` degrades to the exact
    :func:`pmean` with an untouched residual."""
    check_compress_mode(mode)
    if mode == "none":
        return pmean(tree, axis_name), residual
    treedef, fleaves, fidx, leaves = _split_float_leaves(tree)
    if not fleaves:
        return pmean(tree, axis_name), residual
    world = _compat_axis_size(axis_name)
    res_leaves = jax.tree_util.tree_leaves(residual)
    if len(res_leaves) != len(leaves):
        raise ValueError(
            f"residual tree has {len(res_leaves)} leaves, expected "
            f"{len(leaves)} (init with init_error_feedback)"
        )
    fres = [res_leaves[i] for i in fidx]
    p = [g.astype(jnp.float32) + r for g, r in zip(fleaves, fres)]
    logical = _nbytes(fleaves)
    exact = [l for i, l in enumerate(leaves) if i not in set(fidx)]
    if exact:
        exact = pmean(exact, axis_name)
    if mode == "bf16":
        cast = [x.astype(jnp.bfloat16) for x in p]
        _tally_compressed(logical, _nbytes(cast))
        summed = psum(cast, axis_name)
        fmean = [
            (s.astype(jnp.float32) / world).astype(l.dtype)
            for s, l in zip(summed, fleaves)
        ]
        new_res = [x - c.astype(jnp.float32) for x, c in zip(p, cast)]
    else:  # int8
        flat = _chunk_pad(_fuse_f32(p), chunk_size)
        blocks = flat.reshape(-1, chunk_size)
        q, scale, zp, _ = _int8_qparams(blocks, axis_name, world)
        _tally_compressed(logical, q.size + 8 * q.shape[0])
        own = scale * q.astype(jnp.float32) + zp  # this replica's C(p)
        res_flat = (blocks - own).reshape(-1)
        sumq = psum(q, axis_name)
        _record_int8_headroom(sumq)
        mean_flat = (
            (scale * sumq.astype(jnp.float32) + world * zp) / world
        ).reshape(-1)
        fmean = _unfuse(mean_flat, fleaves)
        new_res = _unfuse(res_flat, p, cast=False)
    res_out = list(res_leaves)
    for i, r in zip(fidx, new_res):
        res_out[i] = r
    return (
        _reassemble(treedef, leaves, fidx, fmean, exact),
        jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(residual), res_out
        ),
    )


def compressed_reduce_scatter(
    x: jax.Array,
    axis_name: str = DATA_AXIS,
    *,
    mode: str,
    want_residual: bool = False,
) -> tuple[jax.Array, jax.Array | None]:
    """Compressed ReduceScatter for the ZeRO path: ``x`` is a flat
    vector whose length divides by the world size (the ``FlatLayout``
    invariant); returns ``(summed local shard as f32, residual)``.

    int8 quantizes per scatter shard (the chunk boundaries ARE the
    shard boundaries, so each device dequantizes its own shard with one
    locally-selected scale/zero-point pair); the same shared-range
    overflow budget as :func:`compressed_psum` makes the s8
    ReduceScatter exact on the wire. ``want_residual`` additionally
    returns this replica's full-size compression error (f32, shape of
    ``x``) for error feedback — under ZeRO the residual is inherently
    per-replica and full-size (1× params in f32 per device; EF's known
    memory cost)."""
    check_compress_mode(mode)
    world = _compat_axis_size(axis_name)
    if x.size % world:
        raise ValueError(
            f"payload size {x.size} must divide by the axis size {world}"
        )
    xf = x.astype(jnp.float32)
    if mode == "none":
        return reduce_scatter(xf, axis_name), (
            jnp.zeros_like(xf) if want_residual else None
        )
    logical = xf.size * 4
    if mode == "bf16":
        cast = xf.astype(jnp.bfloat16)
        _tally_compressed(logical, cast.size * 2)
        shard = reduce_scatter(cast, axis_name).astype(jnp.float32)
        res = xf - cast.astype(jnp.float32) if want_residual else None
        return shard, res
    # int8: one quantization chunk per scatter shard
    blocks = xf.reshape(world, -1)
    q, scale, zp, _ = _int8_qparams(blocks, axis_name, world)
    _tally_compressed(logical, q.size + 8 * world)
    sumq = reduce_scatter(q.reshape(-1), axis_name)
    _record_int8_headroom(sumq)
    me = lax.axis_index(axis_name)
    s_me = jnp.take(scale[:, 0], me)
    zp_me = jnp.take(zp[:, 0], me)
    shard = s_me * sumq.astype(jnp.float32) + world * zp_me
    res = None
    if want_residual:
        own = scale * q.astype(jnp.float32) + zp
        res = (blocks - own).reshape(-1)
    return shard, res


def shuffle_sharded_psum(
    tree: Pytree,
    axis_name: str = DATA_AXIS,
    *,
    num_shards: int | None = None,
    mode: str = "none",
    chunk_size: int = DEFAULT_CHUNK_ELEMS,
) -> Pytree:
    """DS-Sync-style shuffle-sharded all-reduce for large trees (arxiv
    2007.03298): the fused payload is partitioned into ``num_shards``
    shards, and each shard is reduced by its own mixed-radix butterfly
    of ``ppermute``s built over a DIFFERENT rank ordering (the full-world
    group rotated by the shard index, through the same
    :func:`_stage_perm` machinery as :func:`psum_in_groups`). Every
    shard's exchange schedule therefore uses different neighbor links at
    each stage — the divide-and-shuffle idea: same total bytes as one
    butterfly, but the per-stage traffic spreads across the torus links
    instead of serializing on one ring, which is what helps when the
    tree is large enough to be bandwidth-bound on a single schedule.

    Composes with the wire modes: ``"bf16"`` runs the butterflies on the
    bf16 payload; ``"int8"`` quantizes once up front (shared world range,
    the usual ``127 // world`` element budget, so int8 partial sums stay
    exact through every stage) and dequantizes once at the end.

    Exact for ``"none"`` (pinned against ``lax.psum``); the result is
    numerically identical on every replica but typed device-varying —
    callers inside ``shard_map`` should declare a varying out-spec or
    re-reduce, which is why the trainers wire :func:`compressed_pmean`
    (unvarying by construction) rather than this variant."""
    check_compress_mode(mode)
    world = _compat_axis_size(axis_name)
    if world == 1:
        return tree
    shards = world if num_shards is None else int(num_shards)
    if shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {shards}")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    flat = _fuse_f32(leaves)
    logical = flat.size * 4
    scale = zp = None
    if mode == "bf16":
        payload = flat.astype(jnp.bfloat16)
        _tally_compressed(logical, payload.size * 2)
    elif mode == "int8":
        blocks = _chunk_pad(flat, chunk_size).reshape(-1, chunk_size)
        q, scale, zp, _ = _int8_qparams(blocks, axis_name, world)
        payload = q.reshape(-1)
        _tally_compressed(logical, payload.size + 8 * blocks.shape[0])
    else:
        payload = flat
    payload_size = payload.size
    pad = (-payload_size) % shards
    if pad:
        payload = jnp.concatenate(
            [payload, jnp.zeros((pad,), payload.dtype)]
        )
    segs = payload.reshape(shards, -1)
    factors = _prime_factors(world)
    outs = []
    for j in range(shards):
        # shard j's butterfly runs over the world rotated by j: same
        # stage count, different (src, dst) links every stage
        order = tuple((r + j) % world for r in range(world))
        seg = segs[j]
        stride = 1
        for f in factors:
            acc = seg
            for k in range(1, f):
                perm = _stage_perm((order,), stride, f, k)
                _tally("ppermute", seg)
                acc = acc + lax.ppermute(seg, axis_name, perm)
            seg = acc
            stride *= f
        outs.append(seg)
    summed = jnp.concatenate(outs)[:payload_size]
    if mode == "bf16":
        summed_flat = summed.astype(jnp.float32)
    elif mode == "int8":
        summed_flat = (
            scale * summed.reshape(-1, chunk_size).astype(jnp.float32)
            + world * zp
        ).reshape(-1)[:flat.size]
    else:
        summed_flat = summed
    out = _unfuse(summed_flat, leaves)
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# live wire-traffic estimation


class DispatchWireTally:
    """Convert trace-time collective inventories into a live per-dispatch
    byte counter (``collectives.dispatched_bytes``).

    The ``collectives.<op>.bytes`` tallies count once per *compilation*
    (:func:`_tally`): a steady-state loop re-executing one compiled
    program moves real bytes every step while the tallies stand still —
    so a rate window over them reads zero exactly when traffic is
    highest. This tally closes the gap: when a dispatch grows the
    trace-time total (a compile happened inside it), the delta is that
    program's per-execution inventory; every dispatch then replays the
    inventory into ``collectives.dispatched_bytes`` (× ``steps`` for
    fused K-step programs — scan bodies tally once but execute K times,
    the same K-invariance the program contracts pin). The windowed
    aggregator (``obs.timeseries``) turns that counter into the live
    bytes/s a network-bound diagnosis or an EQuARX-style compression
    argument needs (PAPERS.md, arXiv:2007.03298 / 2506.17615).

    An estimate, not an exact meter: a concurrent compile on another
    thread (e.g. a serve bucket warming) lands in whichever dispatch
    observes it first. Driven by ``ResilientLoop``; no-op while
    telemetry is disabled."""

    def __init__(self):
        self._program_bytes = 0
        self._last_total = self._traced_total()

    @staticmethod
    def _traced_total() -> int:
        return traced_bytes_total()

    def after_dispatch(self, steps: int = 1) -> None:
        """Record one executed program dispatch covering ``steps``
        optimizer steps."""
        if not telemetry.enabled():
            return
        total = self._traced_total()
        if total > self._last_total:
            # a (re)trace happened inside this dispatch: its delta is
            # the new program's per-execution collective inventory
            self._program_bytes = total - self._last_total
            self._last_total = total
        if self._program_bytes:
            telemetry.count("collectives.dispatched_bytes",
                            self._program_bytes * max(1, int(steps)))
