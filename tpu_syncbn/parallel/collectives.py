"""Collective communication over mesh axes — the TPU-native replacement for
the reference stack's NCCL process-group layer.

The reference recipe's collectives (reference ``README.md:29-35`` selects the
``'nccl'`` backend; the ops its stack actually issues are pinned in SURVEY §5.8):

* ``all_gather(_single)`` — SyncBN forward stats exchange
  (``[torch] nn/modules/_functions.py:74-86``)
* ``all_reduce(SUM)`` — SyncBN backward (``:160-165``) + DDP gradient buckets
* ``broadcast`` — DDP init-time parameter sync
  (``[torch] nn/parallel/distributed.py:1066-1072``)

Here each op is a thin wrapper over ``jax.lax`` named-axis collectives, legal
inside any ``shard_map``/``pmap``-traced function over a mesh axis. XLA lowers
them to AllReduce/AllGather/CollectivePermute HLOs scheduled over ICI/DCN —
compiler-scheduled rather than runtime-issued, which subsumes NCCL stream
management and DDP's bucketing/overlap machinery (the latency-hiding
scheduler overlaps them with compute automatically).

Also hosts :func:`reduce_moments` — the count-weighted cross-replica moment
reduction that is the numerical core of SyncBatchNorm (the TPU-native
equivalent of ``batch_norm_gather_stats_with_counts``,
``[torch] nn/modules/_functions.py:106-115``): replicas contribute
(sum, sumsq, count) and receive exact global (mean, biased var, count),
correct for uneven/empty shards.
"""

from __future__ import annotations

import math
import operator
import threading
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpu_syncbn.compat import axis_size as _compat_axis_size
from tpu_syncbn.obs import telemetry
from tpu_syncbn.runtime.distributed import DATA_AXIS

Pytree = Any

#: Running total of trace-time collective payload bytes (every _tally
#: adds here alongside the per-op counters) — the O(1) read that lets
#: DispatchWireTally run on the step loop without snapshotting the
#: registry per dispatch.
_traced_bytes_lock = threading.Lock()
_traced_bytes_total = 0


def traced_bytes_total() -> int:
    """Trace-time collective bytes tallied so far in this process."""
    with _traced_bytes_lock:
        return _traced_bytes_total


def _tally(op: str, tree: Pytree) -> None:
    """Per-op call + estimated-byte counters (``collectives.<op>.calls``
    / ``.bytes``) when telemetry is enabled.

    These count at **trace time**: collectives in this module execute
    while XLA traces the step program, once per compilation, not once
    per step — so the tallies are the per-program collective inventory
    (DS-Sync's "how much does this step synchronize", arxiv 2007.03298).
    Per-execution traffic is this estimate times the step count; the
    payload estimate is the mathematical per-replica input size
    (shape × itemsize), which for an all-reduce equals what ring
    algorithms move within a factor of 2(N-1)/N."""
    if not telemetry.enabled():
        return
    nbytes = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        try:
            shape = tuple(getattr(leaf, "shape", ()))
            dtype = getattr(leaf, "dtype", None)
            itemsize = np.dtype(dtype).itemsize if dtype is not None else 0
            nbytes += int(math.prod(shape)) * itemsize
        except (TypeError, ValueError):
            continue  # abstract/dynamic leaf: skip, keep the call count
    telemetry.count(f"collectives.{op}.calls")
    telemetry.count(f"collectives.{op}.bytes", nbytes)
    # O(1) running total for DispatchWireTally — reading it per dispatch
    # must not pay a full registry snapshot on the step loop's hot path
    global _traced_bytes_total
    with _traced_bytes_lock:
        _traced_bytes_total += nbytes


def axis_size(axis_name: str = DATA_AXIS) -> int:
    """World size along a mesh axis — the reference's ``world_size``
    (``README.md:33``), available inside the compiled step."""
    return _compat_axis_size(axis_name)


def axis_index(axis_name: str = DATA_AXIS) -> jax.Array:
    """This replica's index along a mesh axis — the reference's ``rank``
    (``README.md:34``), as a traced scalar."""
    return lax.axis_index(axis_name)


def psum(tree: Pytree, axis_name: str = DATA_AXIS) -> Pytree:
    """Sum every leaf across the axis: ``dist.all_reduce(SUM)``
    (as used by SyncBN backward, ``[torch] nn/modules/_functions.py:160-165``)."""
    _tally("psum", tree)
    return lax.psum(tree, axis_name)


def pmean(tree: Pytree, axis_name: str = DATA_AXIS) -> Pytree:
    """Mean every leaf across the axis — all_reduce followed by the divide
    DDP's reducer applies to gradients (``[torch] nn/parallel/distributed.py``
    Reducer grad averaging)."""
    _tally("pmean", tree)
    return lax.pmean(tree, axis_name)


def pmax(tree: Pytree, axis_name: str = DATA_AXIS) -> Pytree:
    """Elementwise max across the axis (all_reduce(MAX))."""
    _tally("pmax", tree)
    return lax.pmax(tree, axis_name)


def pmin(tree: Pytree, axis_name: str = DATA_AXIS) -> Pytree:
    """Elementwise min across the axis (all_reduce(MIN))."""
    _tally("pmin", tree)
    return lax.pmin(tree, axis_name)


def all_gather(
    tree: Pytree,
    axis_name: str = DATA_AXIS,
    *,
    axis: int = 0,
    tiled: bool = False,
) -> Pytree:
    """Gather every replica's leaf along a new (or tiled) leading axis:
    ``dist.all_gather_into_tensor`` (SyncBN forward stats exchange,
    ``[torch] nn/modules/_functions.py:74-77``)."""
    _tally("all_gather", tree)
    return lax.all_gather(tree, axis_name, axis=axis, tiled=tiled)


def broadcast(tree: Pytree, src: int = 0, axis_name: str = DATA_AXIS) -> Pytree:
    """Every replica receives replica ``src``'s value: ``dist.broadcast``
    (DDP init-time param/buffer sync from rank 0,
    ``[torch] nn/parallel/distributed.py:1066-1072``).

    SPMD formulation: gather all replicas' values and select ``src``'s.
    XLA folds the gather+index; for the init-time use the cost is a one-off.
    """
    _tally("broadcast", tree)
    size = _compat_axis_size(axis_name)  # static at trace time
    if not -size <= src < size:
        raise ValueError(
            f"broadcast src={src} out of range for axis {axis_name!r} of size {size}"
        )
    src = src % size
    # psum of the masked value: no world_size× gather buffer, one AllReduce.
    is_src = lax.axis_index(axis_name) == src

    def one(x):
        return lax.psum(jnp.where(is_src, x, jnp.zeros_like(x)), axis_name)

    return jax.tree_util.tree_map(one, tree)


def pcast_varying(tree: Pytree, axis_name: str = DATA_AXIS) -> Pytree:
    """Idempotently cast every leaf to device-varying over ``axis_name``
    (``lax.pcast`` raises on an already-varying input, and mixed trees are
    common: SyncBN stats come out of their psum unvarying while plain-BN
    stats stay varying). Shared home for the VMA-cast used by the
    trainers and the sequence-parallel scan carries — one place to adapt
    if jax's vma/pcast API shifts again."""

    from tpu_syncbn import compat

    if not compat.HAS_VMA:
        return tree  # pre-VMA jax: no varying type to cast to

    def leaf(x):
        if axis_name in getattr(jax.typeof(x), "vma", frozenset()):
            return x
        return lax.pcast(x, axis_name, to="varying")

    return jax.tree_util.tree_map(leaf, tree)


def ppermute(
    tree: Pytree, perm: list[tuple[int, int]], axis_name: str = DATA_AXIS
) -> Pytree:
    """Point-to-point ring/permutation sends (CollectivePermute over ICI).
    No reference analogue in the recipe; exposed for ring-style algorithms."""
    _tally("ppermute", tree)
    return lax.ppermute(tree, axis_name, perm)


def all_to_all(
    tree: Pytree,
    axis_name: str = DATA_AXIS,
    *,
    split_axis: int = 0,
    concat_axis: int = 0,
    tiled: bool = True,
) -> Pytree:
    """All-to-all resharding (sequence/expert-parallel building block).
    Not used by the reference recipe; exposed as the mesh-ready extension
    point SURVEY §2 calls for."""
    _tally("all_to_all", tree)
    return lax.all_to_all(
        tree, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def reduce_scatter(
    x: jax.Array, axis_name: str = DATA_AXIS, *, scatter_dimension: int = 0
) -> jax.Array:
    """Sum across the axis, then shard the result along ``scatter_dimension``
    (ReduceScatter HLO). The building block for ZeRO-style sharded optimizer
    states (out of reference scope, SURVEY §2, but mesh-ready)."""
    _tally("reduce_scatter", x)
    return lax.psum_scatter(
        x, axis_name, scatter_dimension=scatter_dimension, tiled=True
    )


def _prime_factors(n: int) -> list:
    """Ascending prime factorization (with multiplicity); empty for 1."""
    fs, f = [], 2
    while n > 1:
        while n % f == 0:
            fs.append(f)
            n //= f
        f += 1 if f == 2 else 2
    return fs


def _stage_perm(
    groups: tuple, stride: int, f: int, k: int
) -> list:
    """(source, dest) ppermute pairs for shift ``k`` of a radix-``f``
    mixed-radix butterfly stage at ``stride``, within equal-size replica
    ``groups`` (arbitrary membership): each member receives from the
    group member whose position digit at this stride is ``k`` ahead
    (mod f). Contiguous groups are the special case
    ``groups[i] = range(i*g, (i+1)*g)``."""
    perm = []
    for g in groups:
        for pos, rank in enumerate(g):
            d = (pos // stride) % f
            src_pos = pos + (((d + k) % f) - d) * stride
            perm.append((g[src_pos], rank))
    return perm


def normalize_group_spec(group_size):
    """Canonicalize a ``group_size`` value: an int-like scalar stays an
    int (contiguous groups of that size); anything else must be a rank
    partition and becomes hashable nested tuples of exact ints
    (``operator.index`` — a non-integral rank like 1.9 is an error, not
    a silent truncation). ONE normalization shared by ``SyncBatchNorm``,
    ``convert_sync_batchnorm`` and ``psum_in_groups`` so the value
    hashes/compares identically across jit cache keys. ``None`` passes
    through (full-world sync)."""
    if group_size is None:
        return None
    if isinstance(group_size, bool):
        raise ValueError(f"group_size must be an int or a rank "
                         f"partition, got {group_size!r}")
    try:
        return operator.index(group_size)  # int, np.integer, ...
    except TypeError:
        pass
    try:
        return tuple(tuple(operator.index(r) for r in g)
                     for g in group_size)
    except (TypeError, ValueError) as e:
        raise ValueError(
            "group_size must be an int or a sequence of rank "
            f"sequences of exact integers, got {group_size!r}"
        ) from e


def _validate_partition(world: int, groups: tuple) -> tuple:
    """Check a normalized rank partition: every rank in [0, world)
    exactly once, no empty groups. Returns it unchanged."""
    flat = [r for g in groups for r in g]
    if any(not g for g in groups) or sorted(flat) != list(range(world)):
        raise ValueError(
            f"groups {groups!r} must partition ranks 0..{world - 1}: "
            "every rank exactly once, no empty groups (torch builds its "
            "process groups under the same constraint — "
            "[torch] distributed/distributed_c10d.py new_group)"
        )
    return groups


def psum_in_groups(
    tree: Pytree, axis_name: str, group_size
) -> Pytree:
    """Sum within replica subgroups along the axis — the TPU form of
    torch's ``process_group`` scoping (e.g. SyncBN synced within a node
    rather than the whole world).

    ``group_size`` is either

    * an ``int`` g: contiguous groups ``[0..g), [g..2g), ...`` (g must
      divide the axis size) — the common topology-shaped case, or
    * an explicit partition — a sequence of rank sequences covering
      every rank exactly once, e.g. ``((0, 3, 5, 6), (1, 2, 4, 7))`` —
      matching the arbitrary rank sets torch's ``process_group``
      accepts (``[torch] nn/modules/batchnorm.py:706``).

    ``lax.psum(axis_index_groups=...)`` is unimplemented under shard_map's
    VMA checker (jax 0.9: the type system cannot express a group-varying
    reduce result), so equal-size groups take a **mixed-radix butterfly**
    of ``ppermute``s: the group size is factorized and each prime factor
    ``f`` contributes one stage of ``f - 1`` shifted exchanges —
    O(payload · Σ(fᵢ − 1)) traffic for ANY group size (log₂ g messages
    when g is a power of two, where radix-2 stages reduce to the classic
    recursive-doubling XOR butterfly), never an O(world) gather. All
    perms are compile-time constants, VMA-legal CollectivePermute HLOs;
    for contiguous groups XLA schedules them over the direct ICI
    neighbor links the groups sit on (arbitrary-membership groups keep
    the same message count but may route across the mesh). The whole
    tree moves as ONE fused payload, keeping the "one collective per BN
    layer" property.

    Unequal-size groups cannot share one butterfly schedule (stage
    counts differ per group), so they fall back to a masked all-gather:
    one AllGather of the fused payload plus a per-replica constant
    membership row — O(world · payload) traffic, the same order as the
    reference's SyncBN stats exchange (``all_gather`` of every rank's
    stats, ``[torch] nn/modules/_functions.py:74-86``), so the fallback
    is never worse than the semantics it emulates.

    Latency note: a large *prime* factor f contributes f-1 dependent
    exchange rounds (ring-like latency), so e.g. g=13 pays 12 round
    trips where a gather would pay one. Real stat-sync groups are
    topology-shaped (2/4/8 replicas per host, occasionally 3/6), where
    Σ(fᵢ−1) ≤ 4 — the design targets those; for exotic large-prime
    groups prefer ``group_size=None`` (full-world psum) or an explicit
    unequal partition (which takes the gather path).
    """
    world = _compat_axis_size(axis_name)
    group_size = normalize_group_spec(group_size)
    if isinstance(group_size, int):
        if group_size < 1 or world % group_size:
            raise ValueError(
                f"group_size {group_size} must divide axis size {world}"
            )
        if group_size == world:
            return lax.psum(tree, axis_name)
        groups = tuple(
            tuple(range(i, i + group_size))
            for i in range(0, world, group_size)
        )
    else:
        groups = _validate_partition(world, group_size)
        if len(groups) == 1:
            return lax.psum(tree, axis_name)

    # one fused payload for the whole tree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    flat = jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for l in leaves])

    sizes = {len(g) for g in groups}
    if len(sizes) == 1:
        stride = 1
        for f in _prime_factors(sizes.pop()):
            # radix-f stage: each member sums the f values whose
            # mixed-radix position digit at this stride differs — after
            # the stage, every member holds the sum over its digit
            # group; after all stages, the full group sum
            acc = flat
            for k in range(1, f):
                perm = _stage_perm(groups, stride, f, k)
                acc = acc + lax.ppermute(flat, axis_name, perm)
            flat = acc
            stride *= f
        summed = flat
    else:
        # masked gather: every replica sees every row, sums its group's
        gathered = lax.all_gather(flat, axis_name)  # (world, payload)
        member = [[0.0] * world for _ in range(world)]
        for g in groups:
            for i in g:
                for j in g:
                    member[i][j] = 1.0
        row = jnp.take(
            jnp.asarray(member, jnp.float32),
            lax.axis_index(axis_name), axis=0,
        )
        # elementwise mask + sum, NOT a matmul: jnp.matmul at default
        # precision runs bf16 multiply passes on TPU, which would break
        # the f32 accumulation the payload was cast to float32 for
        summed = (row[:, None] * gathered).sum(0)

    out = []
    offset = 0
    for l in leaves:
        n = l.size
        out.append(summed[offset : offset + n].reshape(l.shape).astype(l.dtype))
        offset += n
    return jax.tree_util.tree_unflatten(treedef, out)


def ring_all_reduce(
    x: jax.Array, axis_name: str = DATA_AXIS
) -> jax.Array:
    """Bandwidth-optimal ring all-reduce built from ``ppermute`` steps —
    the explicit form of what NCCL's ring kernels (reference ``'nccl'``
    backend, ``README.md:31``) and XLA's AllReduce do internally.

    reduce-scatter phase: N-1 neighbor hops, each accumulating one 1/N
    chunk; all-gather phase: N-1 hops circulating the finished chunks.
    Total traffic per device: 2·(N-1)/N · payload — the ring optimum.

    ``lax.psum`` (one AllReduce HLO that XLA schedules over ICI) is the
    production path; this exists to (a) pin the ring algebra with tests,
    (b) serve as the template for ring-style long-context algorithms
    (ring attention passes KV blocks around the same neighbor cycle
    while overlapping compute — SURVEY §5.7's extension point).
    """
    n = _compat_axis_size(axis_name)
    if n == 1:
        return x
    orig_shape = x.shape
    flat = jnp.ravel(x)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    chunks = flat.reshape(n, -1)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    me = lax.axis_index(axis_name)

    # reduce-scatter: at step s device ``me`` receives the partial sum of
    # chunk (me - s) from its left neighbor and adds its own copy; after
    # N-1 steps it owns the complete sum of chunk (me + 1) % n
    acc = jnp.take(chunks, me, axis=0)
    for s in range(1, n):
        acc = lax.ppermute(acc, axis_name, fwd)
        acc = acc + jnp.take(chunks, (me - s) % n, axis=0)
    # all-gather: circulate each finished chunk around the ring
    gathered = [acc]
    cur = acc
    for _ in range(n - 1):
        cur = lax.ppermute(cur, axis_name, fwd)
        gathered.append(cur)
    # device me received chunk (me - s + 1) % n at gather step s; restore
    # index order: out[j] = gathered[(me + 1 - j) % n]
    order = jnp.stack(gathered)  # (n, chunk)
    idx = (me + 1 - jnp.arange(n)) % n
    out = jnp.take(order, idx, axis=0).reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(orig_shape)


def reduce_moments(
    local_sum: jax.Array,
    local_sumsq: jax.Array,
    local_count: jax.Array,
    axis_name: str = DATA_AXIS,
    *,
    group_size: int | tuple | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Count-weighted global moments from per-replica partial sums.

    The numerical heart of SyncBatchNorm. The reference all_gathers per-rank
    ``[mean, invstd, count]`` and recombines with
    ``batch_norm_gather_stats_with_counts``
    (``[torch] nn/modules/_functions.py:41-115``) precisely because shards
    may be uneven or empty (``:50-57``). Summing raw (sum, sumsq, count)
    with a single fused ``psum`` is algebraically identical, needs one
    collective instead of an all_gather + recombine, and is exact for
    empty shards (they contribute zeros, matching ``:195-205``).

    Args:
      local_sum:   per-channel sum of x over this replica's local elements.
      local_sumsq: per-channel sum of x² over this replica's local elements.
      local_count: scalar (or per-channel) number of local elements.

    Returns:
      (global_mean, global_biased_var, global_count). Variance is the
      *biased* (1/N) variance — what BN normalizes with; the unbiased
      running-var correction is the caller's job (see ops.batch_norm).
    """
    triple = (local_sum, local_sumsq, local_count)
    if group_size is not None:
        total_sum, total_sumsq, total_count = psum_in_groups(
            triple, axis_name, group_size
        )
    else:
        total_sum, total_sumsq, total_count = lax.psum(triple, axis_name)
    mean, var = moments_from_stats(total_sum, total_sumsq, total_count)
    return mean, var, total_count


def moments_from_stats(
    s: jax.Array, sq: jax.Array, count: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """(mean, biased var) from raw partial sums; safe for count==0, and
    clamps the tiny negative values that cancellation in ``sumsq - n·mean²``
    can produce. Single home for this math — both the local path
    (ops.batch_norm) and the cross-replica path above use it."""
    safe = jnp.maximum(count, 1.0)
    mean = s / safe
    var = jnp.maximum(sq / safe - mean * mean, 0.0)
    return mean, var


# ---------------------------------------------------------------------------
# live wire-traffic estimation


class DispatchWireTally:
    """Convert trace-time collective inventories into a live per-dispatch
    byte counter (``collectives.dispatched_bytes``).

    The ``collectives.<op>.bytes`` tallies count once per *compilation*
    (:func:`_tally`): a steady-state loop re-executing one compiled
    program moves real bytes every step while the tallies stand still —
    so a rate window over them reads zero exactly when traffic is
    highest. This tally closes the gap: when a dispatch grows the
    trace-time total (a compile happened inside it), the delta is that
    program's per-execution inventory; every dispatch then replays the
    inventory into ``collectives.dispatched_bytes`` (× ``steps`` for
    fused K-step programs — scan bodies tally once but execute K times,
    the same K-invariance the program contracts pin). The windowed
    aggregator (``obs.timeseries``) turns that counter into the live
    bytes/s a network-bound diagnosis or an EQuARX-style compression
    argument needs (PAPERS.md, arXiv:2007.03298 / 2506.17615).

    An estimate, not an exact meter: a concurrent compile on another
    thread (e.g. a serve bucket warming) lands in whichever dispatch
    observes it first. Driven by ``ResilientLoop``; no-op while
    telemetry is disabled."""

    def __init__(self):
        self._program_bytes = 0
        self._last_total = self._traced_total()

    @staticmethod
    def _traced_total() -> int:
        return traced_bytes_total()

    def after_dispatch(self, steps: int = 1) -> None:
        """Record one executed program dispatch covering ``steps``
        optimizer steps."""
        if not telemetry.enabled():
            return
        total = self._traced_total()
        if total > self._last_total:
            # a (re)trace happened inside this dispatch: its delta is
            # the new program's per-execution collective inventory
            self._program_bytes = total - self._last_total
            self._last_total = total
        if self._program_bytes:
            telemetry.count("collectives.dispatched_bytes",
                            self._program_bytes * max(1, int(steps)))
