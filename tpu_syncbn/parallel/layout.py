"""One named-sharding layout for the whole program: :class:`SpecLayout`.

ROADMAP item 1. The parallelism layer grew one strategy per module —
``zero.py`` (optimizer-state sharding), ``tensor.py`` (param rules),
``pipeline.py`` (stage axis), plain DP — each constructing its *own*
``Mesh``/``NamedSharding`` plumbing, so strategies could be ranked but
never combined. ``SpecLayout`` is the composition point: one named N-D
mesh (axes canonically from :mod:`tpu_syncbn.mesh_axes`), per-param
``PartitionSpec`` rules with wildcard name matching, and *derived*
reduce/scatter axes for gradients, optimizer state, and SyncBN
statistics. Trainers and the serve engine consume a layout instead of
building meshes (srclint ``private_mesh_plumbing`` polices this), so
``P(('data','fsdp'))`` batch sharding, fsdp-sharded optimizer state,
tensor-parallel param rules, and the pipe axis compose on one mesh in
one compiled program.

Following arXiv:2004.13336, ZeRO is a layout *rule* here, not a trainer
mode: ``zero=True`` is the :meth:`SpecLayout.zero` preset (shard the
weight update over the lone data axis), and DP×FSDP is the
:meth:`SpecLayout.fsdp` preset (shard over a dedicated ``fsdp`` axis,
reduce the rest of the way over ``data``). Derived axes:

* ``stat_axes`` — SyncBN statistics reduce over *every* batch-sharding
  axis (the paper's point: statistics scope = all replicas, and a
  composed layout has replicas on more than one mesh axis).
* ``grad_reduce_axes`` — full gradient reduction axes for unsharded
  params (plain DP pmean).
* ``grad_scatter_axis`` / ``grad_cross_axes`` — for sharded layouts the
  gradient is reduce-scattered over the shard axis first (full→1/F
  bytes), then the surviving shard is psum'd over the remaining batch
  axes. ``compressed_reduce_scatter``/``compressed_psum`` ride these
  same axes, which is what makes ``compress="int8"`` legal in every
  composition.

Layout legality is explicit: :meth:`reject_reasons` names why a
composition is infeasible (the planner surfaces these verbatim), instead
of failing deep inside a trainer.
"""

from __future__ import annotations

import fnmatch
from typing import Any, Iterable, Mapping, Sequence

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_syncbn.mesh_axes import (
    ALL_AXES,
    DATA_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
)

__all__ = ["SpecLayout"]

#: Axes whose mesh dimension shards the *batch* (replica-like axes). A
#: composed layout's SyncBN/gradient reductions span all of these.
_BATCH_AXES = (DATA_AXIS, FSDP_AXIS)

#: int8 compressed collectives encode the reduction in an i8 accumulator
#: budget: qmax = 127 // world (collectives._int8_qparams).
_INT8_MAX_WORLD = 127


def _rank_name(entry: Any) -> Iterable[str]:
    """Axis names referenced by one PartitionSpec entry."""
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


class SpecLayout:
    """A named mesh plus the sharding rules every consumer derives from.

    Parameters
    ----------
    axis_sizes:
        Mapping of canonical axis name (:data:`~tpu_syncbn.mesh_axes.ALL_AXES`)
        to mesh dimension. At most one entry may be ``-1`` ("all remaining
        devices"). Ignored when ``mesh`` is given.
    rules:
        Sequence of ``(pattern, PartitionSpec)`` pairs matched against
        ``/``-joined parameter paths with :func:`fnmatch.fnmatchcase`
        (first match wins; unmatched params are replicated). This is how
        tensor-parallel layouts name their sharded matrices, e.g.
        ``("*/attn/qkv/kernel", P(None, 'model'))``.
    param_shard_axis:
        Mesh axis the flat parameter/optimizer-state shards live on
        (ZeRO/FSDP), or ``None`` for replicated params. The default
        ``"auto"`` picks the ``fsdp`` axis when the mesh has one.
    devices:
        Optional explicit device sequence (defaults to ``jax.devices()``).
    mesh:
        Adopt an existing mesh instead of building one. Its axis names
        must be canonical and in :data:`ALL_AXES` order.
    """

    def __init__(
        self,
        axis_sizes: Mapping[str, int] | None = None,
        *,
        rules: Sequence[tuple[str, P]] = (),
        param_shard_axis: str | None = "auto",
        devices: Sequence[Any] | None = None,
        mesh: Any | None = None,
    ) -> None:
        from tpu_syncbn.runtime import distributed as dist

        if mesh is not None:
            names = tuple(mesh.axis_names)
        else:
            if not axis_sizes:
                axis_sizes = {DATA_AXIS: -1}
            names = tuple(axis_sizes)
        unknown = [a for a in names if a not in ALL_AXES]
        if unknown:
            raise ValueError(
                f"unknown mesh axes {unknown}; canonical axes are {list(ALL_AXES)}"
                " (tpu_syncbn.mesh_axes)"
            )
        order = sorted(names, key=ALL_AXES.index)
        if mesh is not None:
            if tuple(order) != names:
                raise ValueError(
                    f"mesh axes {list(names)} out of canonical order; expected"
                    f" {order} (data-like outermost — mesh_axes.ALL_AXES)"
                )
            self.mesh = mesh
        else:
            sizes = {a: int(axis_sizes[a]) for a in order}
            self.mesh = dist.make_mesh(sizes, devices=devices)

        self.axis_sizes: dict[str, int] = {
            a: int(self.mesh.shape[a]) for a in self.mesh.axis_names
        }
        self.rules: tuple[tuple[str, P], ...] = tuple(
            (str(pat), spec) for pat, spec in rules
        )
        for pat, spec in self.rules:
            for entry in spec:
                for a in _rank_name(entry):
                    if a not in self.axis_sizes:
                        raise ValueError(
                            f"rule {pat!r} names axis {a!r} not in mesh"
                            f" {list(self.axis_sizes)}"
                        )

        if param_shard_axis == "auto":
            param_shard_axis = FSDP_AXIS if FSDP_AXIS in self.axis_sizes else None
        if param_shard_axis is not None:
            if param_shard_axis not in self.axis_sizes:
                raise ValueError(
                    f"param_shard_axis {param_shard_axis!r} not in mesh"
                    f" {list(self.axis_sizes)}"
                )
            if param_shard_axis not in _BATCH_AXES:
                raise ValueError(
                    f"param_shard_axis {param_shard_axis!r} must be a"
                    f" batch-sharding axis {list(_BATCH_AXES)}: flat ZeRO/FSDP"
                    " shards divide the *replicated* weight update"
                )
        self.param_shard_axis: str | None = param_shard_axis

        # ---- derived axes --------------------------------------------
        #: batch-sharding axes present in the mesh, canonical order
        self.data_axes: tuple[str, ...] = tuple(
            a for a in _BATCH_AXES if a in self.axis_sizes
        )
        #: the PartitionSpec *entry* for the batch dimension: a plain
        #: string for 1-D layouts (keeps single-axis programs and their
        #: pinned goldens byte-identical), a tuple when composed, None
        #: when the mesh has no batch axis (pure TP serving)
        self.batch_entry: str | tuple[str, ...] | None = None
        if len(self.data_axes) == 1:
            self.batch_entry = self.data_axes[0]
        elif self.data_axes:
            self.batch_entry = self.data_axes
        #: axes SyncBN statistics reduce over (== batch axes)
        self.stat_axes = self.batch_entry
        #: axes a full (unsharded) gradient pmean runs over
        self.grad_reduce_axes = self.batch_entry
        #: axis the flat grad is reduce-scattered over (None: no scatter)
        self.grad_scatter_axis = param_shard_axis
        #: batch axes left to psum after the scatter stage
        self.grad_cross_axes: tuple[str, ...] = tuple(
            a for a in self.data_axes if a != param_shard_axis
        )
        #: total number of batch replicas (gradient-mean divisor)
        self.replica_world: int = 1
        for a in self.data_axes:
            self.replica_world *= self.axis_sizes[a]
        #: devices each flat param shard is divided over
        self.shard_world: int = (
            self.axis_sizes[param_shard_axis] if param_shard_axis else 1
        )
        #: total devices in the mesh
        self.world: int = int(self.mesh.size)

    # ---- constructors (the presets) ----------------------------------

    @classmethod
    def data_parallel(
        cls, num_replicas: int | None = None, *, devices=None, rules=()
    ) -> "SpecLayout":
        """Plain DP: 1-D ``data`` mesh, replicated params."""
        return cls(
            {DATA_AXIS: -1 if num_replicas is None else num_replicas},
            rules=rules, param_shard_axis=None, devices=devices,
        )

    @classmethod
    def zero(
        cls, num_replicas: int | None = None, *, devices=None
    ) -> "SpecLayout":
        """Today's ``zero=True``: 1-D ``data`` mesh, flat param/opt shards
        over the same axis (parity-pinned against the legacy flag)."""
        return cls(
            {DATA_AXIS: -1 if num_replicas is None else num_replicas},
            param_shard_axis=DATA_AXIS, devices=devices,
        )

    @classmethod
    def fsdp(
        cls, *, data: int = -1, fsdp: int, devices=None, rules=()
    ) -> "SpecLayout":
        """Composed DP×FSDP: 2-D ``('data','fsdp')`` mesh, batch sharded
        ``P(('data','fsdp'))``, flat param/opt shards over ``fsdp``."""
        return cls(
            {DATA_AXIS: data, FSDP_AXIS: fsdp},
            param_shard_axis=FSDP_AXIS, devices=devices, rules=rules,
        )

    @classmethod
    def tensor_parallel(
        cls, *, data: int = -1, model: int, rules: Sequence[tuple[str, P]],
        devices=None,
    ) -> "SpecLayout":
        """Composed DP×TP: 2-D ``('data','model')`` mesh; ``rules`` name
        the tensor-sharded params."""
        return cls(
            {DATA_AXIS: data, MODEL_AXIS: model},
            rules=rules, param_shard_axis=None, devices=devices,
        )

    @classmethod
    def from_mesh(
        cls, mesh, *, rules=(), param_shard_axis: str | None = "auto"
    ) -> "SpecLayout":
        """Wrap an existing canonical-axis mesh (e.g. ``pipeline_mesh``)."""
        return cls(mesh=mesh, rules=rules, param_shard_axis=param_shard_axis)

    # ---- shardings ----------------------------------------------------

    def sharding(self, spec: P) -> NamedSharding:
        """A ``NamedSharding`` of ``spec`` on this layout's mesh — the one
        place trainers/engines get shardings from."""
        return NamedSharding(self.mesh, spec)

    @property
    def replicated(self) -> NamedSharding:
        return self.sharding(P())

    @property
    def batch_spec(self) -> P:
        """Leading-dim batch spec: ``P('data')``, ``P(('data','fsdp'))``…"""
        return P(self.batch_entry) if self.batch_entry is not None else P()

    @property
    def batch_sharding(self) -> NamedSharding:
        return self.sharding(self.batch_spec)

    # ---- per-param rules ----------------------------------------------

    def spec_for(self, name: str) -> P:
        """PartitionSpec for one ``/``-joined param path (first matching
        wildcard rule wins; default replicated)."""
        for pat, spec in self.rules:
            if fnmatch.fnmatchcase(name, pat):
                return spec
        return P()

    def param_specs(self, tree) -> Any:
        """Tree of PartitionSpecs matching ``tree``, one per leaf, from
        the wildcard rules."""
        paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
        specs = [self.spec_for(_path_str(path)) for path, _ in paths_leaves]
        return jax.tree_util.tree_unflatten(treedef, specs)

    def param_shardings(self, tree) -> Any:
        return jax.tree_util.tree_map(
            self.sharding, self.param_specs(tree),
            is_leaf=lambda x: isinstance(x, P),
        )

    # ---- legality ------------------------------------------------------

    def reject_reasons(
        self, *, compress: str = "none", group_size: int | None = None
    ) -> list[str]:
        """Why this layout (with these knobs) cannot train — empty when
        legal. Reasons are stable strings the planner reports verbatim."""
        reasons: list[str] = []
        if compress == "int8":
            if self.shard_world > _INT8_MAX_WORLD:
                reasons.append(
                    f"layout: int8 accumulator budget needs shard world"
                    f" <= {_INT8_MAX_WORLD}, got {self.shard_world}"
                )
            cross = 1
            for a in self.grad_cross_axes:
                cross *= self.axis_sizes[a]
            if self.param_shard_axis is None:
                cross = self.replica_world
            if cross > _INT8_MAX_WORLD:
                reasons.append(
                    f"layout: int8 accumulator budget needs reduce world"
                    f" <= {_INT8_MAX_WORLD}, got {cross}"
                )
        if group_size is not None and isinstance(self.stat_axes, tuple):
            reasons.append(
                "layout: grouped BN stats need a single stat axis"
                " (the butterfly permutation is 1-D); composed layout"
                f" syncs over {self.stat_axes}"
            )
        if self.param_shard_axis is not None and MODEL_AXIS in self.axis_sizes:
            reasons.append(
                "layout: fsdp×tensor param sharding not implemented"
                " (flat ZeRO shards and per-param rules both own the params)"
            )
        if self.param_shard_axis is not None and PIPE_AXIS in self.axis_sizes:
            reasons.append(
                "layout: fsdp×pipe not implemented (PipelineTrainer"
                " shards params over the pipe axis)"
            )
        if not self.data_axes and self.param_shard_axis is not None:
            reasons.append("layout: param sharding needs a batch axis")
        return reasons

    def check(self, *, compress: str = "none", group_size=None) -> None:
        """Raise ``ValueError`` with every named reason when illegal."""
        reasons = self.reject_reasons(compress=compress, group_size=group_size)
        if reasons:
            raise ValueError("; ".join(reasons))

    # ---- misc ----------------------------------------------------------

    def describe(self) -> dict:
        """Loggable summary (docs/LAYOUT.md table rows come from this)."""
        return {
            "axes": dict(self.axis_sizes),
            "batch_spec": str(self.batch_spec),
            "param_shard_axis": self.param_shard_axis,
            "grad_cross_axes": list(self.grad_cross_axes),
            "replica_world": self.replica_world,
            "shard_world": self.shard_world,
            "rules": [(pat, str(spec)) for pat, spec in self.rules],
        }

    def __eq__(self, other) -> bool:
        if not isinstance(other, SpecLayout):
            return NotImplemented
        return (
            self.mesh == other.mesh
            and self.rules == other.rules
            and self.param_shard_axis == other.param_shard_axis
        )

    def __hash__(self) -> int:
        return hash((self.mesh, self.rules, self.param_shard_axis))

    def __repr__(self) -> str:
        axes = ",".join(f"{a}={n}" for a, n in self.axis_sizes.items())
        shard = f", shard={self.param_shard_axis}" if self.param_shard_axis else ""
        nrules = f", rules={len(self.rules)}" if self.rules else ""
        return f"SpecLayout({axes}{shard}{nrules})"


def _path_str(path) -> str:
    """``/``-joined name for one tree_flatten_with_path key path."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)
