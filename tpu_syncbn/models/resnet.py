"""ResNet family (nnx, NHWC) — the recipe's model side.

The reference's capability configs (BASELINE.json) name ResNet-18 (CIFAR-10)
and ResNet-50 (ImageNet) as the DP+SyncBN workloads; torchvision's resnet is
the de-facto architecture definition. This is a TPU-first reimplementation:
channel-last layout (lane dim = channels), ``nnx.Conv`` lowering to XLA
convolutions that tile onto the MXU, and a ``norm`` factory argument so
``convert_sync_batchnorm`` (or direct ``SyncBatchNorm`` construction) slots
in without touching the architecture.

``small_input=True`` selects the CIFAR stem (3×3/1 conv, no max-pool) used
by the ResNet-18/CIFAR-10 capability config; default is the ImageNet stem
(7×7/2 + 3×3/2 max-pool).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from flax import nnx

from tpu_syncbn import compat

from tpu_syncbn.nn import BatchNorm2d

# torch resnet uses Kaiming/He fan-out normal for convs
_conv_init = nnx.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")


def _conv(cin, cout, kernel, stride, rngs, *, padding="SAME", dtype=None):
    return nnx.Conv(
        cin, cout, (kernel, kernel), strides=(stride, stride),
        padding=padding, use_bias=False, kernel_init=_conv_init,
        dtype=dtype, param_dtype=jnp.float32, rngs=rngs,
    )


class BasicBlock(nnx.Module):
    expansion = 1

    def __init__(self, cin, planes, stride, norm, rngs, dtype=None):
        self.conv1 = _conv(cin, planes, 3, stride, rngs, dtype=dtype)
        self.bn1 = norm(planes)
        self.conv2 = _conv(planes, planes, 3, 1, rngs, dtype=dtype)
        self.bn2 = norm(planes)
        if stride != 1 or cin != planes * self.expansion:
            self.down_conv = _conv(cin, planes * self.expansion, 1, stride, rngs, dtype=dtype)
            self.down_bn = norm(planes * self.expansion)
        else:
            self.down_conv = None
            self.down_bn = None

    def __call__(self, x):
        identity = x
        out = nnx.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.down_conv is not None:
            identity = self.down_bn(self.down_conv(x))
        return nnx.relu(out + identity)


class Bottleneck(nnx.Module):
    expansion = 4

    def __init__(self, cin, planes, stride, norm, rngs, dtype=None):
        self.conv1 = _conv(cin, planes, 1, 1, rngs, dtype=dtype)
        self.bn1 = norm(planes)
        # torchvision places the stride on the 3x3 (resnet v1.5)
        self.conv2 = _conv(planes, planes, 3, stride, rngs, dtype=dtype)
        self.bn2 = norm(planes)
        self.conv3 = _conv(planes, planes * self.expansion, 1, 1, rngs, dtype=dtype)
        self.bn3 = norm(planes * self.expansion)
        if stride != 1 or cin != planes * self.expansion:
            self.down_conv = _conv(cin, planes * self.expansion, 1, stride, rngs, dtype=dtype)
            self.down_bn = norm(planes * self.expansion)
        else:
            self.down_conv = None
            self.down_bn = None

    def __call__(self, x):
        identity = x
        out = nnx.relu(self.bn1(self.conv1(x)))
        out = nnx.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.down_conv is not None:
            identity = self.down_bn(self.down_conv(x))
        return nnx.relu(out + identity)


class ResNet(nnx.Module):
    """Feature extractor + classifier head.

    ``norm`` is any ``Callable[[int], nnx.Module]`` — the extension point
    the SyncBN conversion relies on (default plain :class:`BatchNorm2d`;
    after ``convert_sync_batchnorm`` every instance is a SyncBatchNorm).
    """

    def __init__(
        self,
        block: type,
        layers: tuple[int, ...],
        *,
        num_classes: int = 1000,
        small_input: bool = False,
        norm: Callable[[int], nnx.Module] | None = None,
        width: int = 64,
        dtype: jnp.dtype | None = None,
        rngs: nnx.Rngs,
    ):
        """``dtype``: compute dtype for convs/matmuls (e.g. jnp.bfloat16
        for the TPU MXU fast path); params stay float32 and BN accumulates
        in float32 regardless."""
        norm = norm if norm is not None else BatchNorm2d
        self.small_input = small_input
        self.dtype = dtype
        if small_input:
            self.stem_conv = _conv(3, width, 3, 1, rngs, dtype=dtype)
        else:
            self.stem_conv = _conv(3, width, 7, 2, rngs, dtype=dtype)
        self.stem_bn = norm(width)

        cin = width
        stages = []
        for i, n_blocks in enumerate(layers):
            planes = width * (2**i)
            stride = 1 if i == 0 else 2
            blocks = []
            for b in range(n_blocks):
                blocks.append(
                    block(cin, planes, stride if b == 0 else 1, norm, rngs,
                          dtype=dtype)
                )
                cin = planes * block.expansion
            stages.append(compat.nnx_list(blocks))
        self.stages = compat.nnx_list(stages)
        self.fc = nnx.Linear(
            cin, num_classes,
            kernel_init=nnx.initializers.normal(0.01),
            dtype=dtype, param_dtype=jnp.float32, rngs=rngs,
        )
        self.feature_dim = cin

    def features(self, x: jax.Array) -> list[jax.Array]:
        """Per-stage feature maps (C2..C5) — consumed by FPN (RetinaNet)."""
        if self.dtype is not None:
            x = x.astype(self.dtype)
        x = nnx.relu(self.stem_bn(self.stem_conv(x)))
        if not self.small_input:
            x = nnx.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        feats = []
        for stage in self.stages:
            for blk in stage:
                x = blk(x)
            feats.append(x)
        return feats

    def __call__(self, x: jax.Array) -> jax.Array:
        x = self.features(x)[-1]
        x = x.mean(axis=(1, 2))  # global average pool
        return self.fc(x)


def resnet18(**kw) -> ResNet:
    return ResNet(BasicBlock, (2, 2, 2, 2), **kw)


def resnet34(**kw) -> ResNet:
    return ResNet(BasicBlock, (3, 4, 6, 3), **kw)


def resnet50(**kw) -> ResNet:
    return ResNet(Bottleneck, (3, 4, 6, 3), **kw)


def resnet101(**kw) -> ResNet:
    return ResNet(Bottleneck, (3, 4, 23, 3), **kw)


def resnet152(**kw) -> ResNet:
    return ResNet(Bottleneck, (3, 8, 36, 3), **kw)


RESNETS = {
    "resnet18": resnet18,
    "resnet34": resnet34,
    "resnet50": resnet50,
    "resnet101": resnet101,
    "resnet152": resnet152,
}
