"""Detection building blocks: anchors, box coding, IoU matching, losses.

Object detection is the reference's flagship SyncBN use case ("this
performance drop is known to happen for object detection models",
reference ``README.md:3``; RetinaNet-R50-FPN at per-chip batch=2 is the
capability config in BASELINE.json). All ops are static-shape and
jit-friendly: ground truth arrives padded to a fixed ``max_boxes`` with a
validity mask, matching is a dense IoU argmax, and losses mask invalid
entries — no data-dependent shapes anywhere (XLA requirement).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


# -- anchors --------------------------------------------------------------


def generate_level_anchors(
    feat_h: int,
    feat_w: int,
    stride: int,
    sizes: Sequence[float],
    ratios: Sequence[float] = (0.5, 1.0, 2.0),
) -> jnp.ndarray:
    """Anchors for one FPN level, (H*W*A, 4) as (x1, y1, x2, y2), centered
    on the stride grid (torchvision AnchorGenerator semantics)."""
    base = []
    for size in sizes:
        area = float(size) ** 2
        for r in ratios:
            w = math.sqrt(area / r)
            h = w * r
            base.append([-w / 2, -h / 2, w / 2, h / 2])
    base_a = jnp.asarray(base, jnp.float32)  # (A, 4)

    cx = (jnp.arange(feat_w, dtype=jnp.float32) + 0.5) * stride
    cy = (jnp.arange(feat_h, dtype=jnp.float32) + 0.5) * stride
    cxg, cyg = jnp.meshgrid(cx, cy, indexing="xy")
    centers = jnp.stack([cxg, cyg, cxg, cyg], axis=-1).reshape(-1, 1, 4)
    return (centers + base_a[None]).reshape(-1, 4)


def retinanet_anchors(
    image_size: tuple[int, int],
    strides: Sequence[int] = (8, 16, 32, 64, 128),
    anchor_scale: float = 4.0,
) -> jnp.ndarray:
    """All-level RetinaNet anchors concatenated: per level, 3 octave scales
    (2^0, 2^1/3, 2^2/3) × 3 ratios, base size ``anchor_scale × stride``."""
    h, w = image_size
    out = []
    for stride in strides:
        sizes = [anchor_scale * stride * (2 ** (o / 3)) for o in range(3)]
        out.append(
            generate_level_anchors(
                math.ceil(h / stride), math.ceil(w / stride), stride, sizes
            )
        )
    return jnp.concatenate(out, axis=0)


# -- box coding -----------------------------------------------------------


def box_encode(boxes: jnp.ndarray, anchors: jnp.ndarray) -> jnp.ndarray:
    """(x1y1x2y2 boxes, anchors) → (dx, dy, dw, dh) regression targets
    (Faster-R-CNN coding, weights 1)."""
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = anchors[..., 0] + 0.5 * aw
    ay = anchors[..., 1] + 0.5 * ah
    bw = jnp.maximum(boxes[..., 2] - boxes[..., 0], 1e-6)
    bh = jnp.maximum(boxes[..., 3] - boxes[..., 1], 1e-6)
    bx = boxes[..., 0] + 0.5 * bw
    by = boxes[..., 1] + 0.5 * bh
    return jnp.stack(
        [(bx - ax) / aw, (by - ay) / ah, jnp.log(bw / aw), jnp.log(bh / ah)],
        axis=-1,
    )


def box_decode(deltas: jnp.ndarray, anchors: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`box_encode`; clamps dw/dh like torchvision
    (log(1000/16) ≈ 4.135) for numerical safety."""
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = anchors[..., 0] + 0.5 * aw
    ay = anchors[..., 1] + 0.5 * ah
    clamp = math.log(1000.0 / 16)
    dx, dy = deltas[..., 0], deltas[..., 1]
    dw = jnp.clip(deltas[..., 2], -clamp, clamp)
    dh = jnp.clip(deltas[..., 3], -clamp, clamp)
    cx = dx * aw + ax
    cy = dy * ah + ay
    w = jnp.exp(dw) * aw
    h = jnp.exp(dh) * ah
    return jnp.stack(
        [cx - 0.5 * w, cy - 0.5 * h, cx + 0.5 * w, cy + 0.5 * h], axis=-1
    )


# -- IoU + matching -------------------------------------------------------


def box_iou(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Pairwise IoU: (N, 4) × (M, 4) → (N, M)."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(a[:, 3] - a[:, 1], 0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def match_anchors(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    *,
    high: float = 0.5,
    low: float = 0.4,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Max-IoU assigner (torchvision Matcher semantics with
    allow_low_quality_matches): per anchor, the best valid GT index or
    -1 (background) / -2 (ignore, between thresholds). Anchors that are the
    argmax for some GT are force-matched to it.

    Returns (matched_idx (N,), max_iou (N,)).
    """
    iou = box_iou(anchors, gt_boxes)  # (N, M)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    matched = jnp.where(
        best_iou >= high, best_gt, jnp.where(best_iou < low, -1, -2)
    )
    # low-quality promotion: for each valid GT, every anchor achieving that
    # GT's best IoU is force-matched to it. Dense formulation (no scatter:
    # padded invalid GTs must not clobber valid promotions — their masked
    # IoU columns argmax to anchor 0). When an anchor ties as best for
    # several GTs, the highest GT index wins, matching torch's sequential
    # overwrite ([torch] Matcher.set_low_quality_matches_).
    gt_best_iou = jnp.max(iou, axis=0)  # (M,)
    ok = gt_valid & (gt_best_iou > 0)
    is_best = (iou >= gt_best_iou[None, :]) & ok[None, :]  # (N, M)
    m = gt_boxes.shape[0]
    rev = is_best[:, ::-1]
    promote_to = (m - 1 - jnp.argmax(rev, axis=1)).astype(jnp.int32)
    has_promo = jnp.any(is_best, axis=1)
    matched = jnp.where(has_promo, promote_to, matched)
    return matched, best_iou


# -- losses ---------------------------------------------------------------


def sigmoid_focal_loss(
    logits: jnp.ndarray,
    targets: jnp.ndarray,
    *,
    alpha: float = 0.25,
    gamma: float = 2.0,
) -> jnp.ndarray:
    """Elementwise sigmoid focal loss (RetinaNet paper; torchvision
    ``sigmoid_focal_loss`` semantics, reduction='none')."""
    import optax

    p = jax.nn.sigmoid(logits)
    ce = optax.sigmoid_binary_cross_entropy(logits, targets)
    p_t = p * targets + (1 - p) * (1 - targets)
    loss = ce * (1 - p_t) ** gamma
    if alpha >= 0:
        alpha_t = alpha * targets + (1 - alpha) * (1 - targets)
        loss = alpha_t * loss
    return loss


def smooth_l1(pred: jnp.ndarray, target: jnp.ndarray, beta: float = 0.1111) -> jnp.ndarray:
    d = jnp.abs(pred - target)
    return jnp.where(d < beta, 0.5 * d * d / beta, d - 0.5 * beta)


# -- host-side NMS (eval post-process) ------------------------------------


def nms(boxes: np.ndarray, scores: np.ndarray, iou_threshold: float = 0.5):
    """Greedy non-maximum suppression on the host (numpy) — the eval
    post-process torchvision runs after RetinaNet decode. Returns indices
    of kept boxes in descending score order."""
    boxes = np.asarray(boxes, np.float32)
    scores = np.asarray(scores, np.float32)
    order = np.argsort(-scores)
    keep = []
    while order.size:
        i = order[0]
        keep.append(int(i))
        if order.size == 1:
            break
        rest = order[1:]
        lt = np.maximum(boxes[i, :2], boxes[rest, :2])
        rb = np.minimum(boxes[i, 2:], boxes[rest, 2:])
        wh = np.clip(rb - lt, 0, None)
        inter = wh[:, 0] * wh[:, 1]
        area_i = max((boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1]), 0)
        area_r = np.clip(boxes[rest, 2] - boxes[rest, 0], 0, None) * np.clip(
            boxes[rest, 3] - boxes[rest, 1], 0, None
        )
        union = area_i + area_r - inter
        iou = np.where(union > 0, inter / union, 0.0)
        order = rest[iou <= iou_threshold]
    return keep


def batched_nms(boxes, scores, classes, iou_threshold: float = 0.5):
    """Per-class NMS (boxes of different classes never suppress each
    other), torchvision.ops.batched_nms semantics."""
    boxes = np.asarray(boxes, np.float32)
    classes = np.asarray(classes)
    if boxes.size == 0:
        return []
    # offset trick: shift each class into a disjoint coordinate region.
    # Normalize to a non-negative origin first — decoded boxes can have
    # negative coordinates near image edges, which would otherwise leak
    # across class regions.
    boxes = boxes - float(boxes.min())
    span = float(boxes.max()) + 1.0
    offsets = classes.astype(np.float32)[:, None] * span
    return nms(boxes + offsets, scores, iou_threshold)
