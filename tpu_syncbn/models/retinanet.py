"""RetinaNet-R50-FPN (nnx, NHWC) — the small-per-chip-batch SyncBN
capability config (BASELINE.json: "RetinaNet-R50-FPN COCO, per-chip
batch=2"; the case the reference's recipe exists for, ``README.md:3``).

TPU-first choices: NHWC everywhere, static anchor tensors baked at
construction for a fixed image size (XLA static shapes), padded ground
truth with validity masks, nearest-neighbor top-down upsampling via
reshape-broadcast (cheap on VPU), and BN only in the backbone (heads use
plain convs like torchvision's retinanet_resnet50_fpn) so
``convert_sync_batchnorm`` syncs exactly the backbone stats.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from flax import nnx

from tpu_syncbn import compat

from tpu_syncbn.models import detection as det
from tpu_syncbn.models.resnet import ResNet, Bottleneck, _conv_init


def _conv3(cin, cout, rngs, *, bias_init=None):
    return nnx.Conv(
        cin, cout, (3, 3), padding="SAME", kernel_init=_conv_init,
        bias_init=bias_init or nnx.initializers.zeros_init(), rngs=rngs,
    )


def _upsample2(x: jax.Array, target_hw: tuple[int, int]) -> jax.Array:
    """Nearest-neighbor 2× upsample then crop to target (handles odd sizes)."""
    n, h, w, c = x.shape
    y = jnp.broadcast_to(x[:, :, None, :, None, :], (n, h, 2, w, 2, c))
    y = y.reshape(n, h * 2, w * 2, c)
    th, tw = target_hw
    return y[:, :th, :tw, :]


class FPN(nnx.Module):
    """Feature Pyramid Network over C3-C5 with P6/P7 extras
    (RetinaNet flavor: P6 = conv stride 2 on C5, P7 = conv stride 2 on
    relu(P6) — torchvision LastLevelP6P7)."""

    def __init__(self, in_channels: tuple[int, int, int], out_channels: int, rngs):
        self.lateral = compat.nnx_list([
            nnx.Conv(c, out_channels, (1, 1), kernel_init=_conv_init, rngs=rngs)
            for c in in_channels
        ])
        self.output = compat.nnx_list([
            _conv3(out_channels, out_channels, rngs) for _ in in_channels
        ])
        self.p6 = nnx.Conv(
            in_channels[-1], out_channels, (3, 3), strides=(2, 2),
            padding="SAME", kernel_init=_conv_init, rngs=rngs,
        )
        self.p7 = nnx.Conv(
            out_channels, out_channels, (3, 3), strides=(2, 2),
            padding="SAME", kernel_init=_conv_init, rngs=rngs,
        )

    def __call__(self, c3, c4, c5):
        laterals = [lat(c) for lat, c in zip(self.lateral, (c3, c4, c5))]
        # top-down pathway
        p5 = laterals[2]
        p4 = laterals[1] + _upsample2(p5, laterals[1].shape[1:3])
        p3 = laterals[0] + _upsample2(p4, laterals[0].shape[1:3])
        p3, p4, p5 = (out(p) for out, p in zip(self.output, (p3, p4, p5)))
        p6 = self.p6(c5)
        p7 = self.p7(nnx.relu(p6))
        return [p3, p4, p5, p6, p7]


class RetinaHead(nnx.Module):
    """Shared classification/regression subnets (4 conv256 + output)."""

    def __init__(self, channels: int, num_anchors: int, num_classes: int, rngs):
        self.cls_tower = compat.nnx_list(
            [_conv3(channels, channels, rngs) for _ in range(4)]
        )
        self.box_tower = compat.nnx_list(
            [_conv3(channels, channels, rngs) for _ in range(4)]
        )
        # focal-loss prior: bias so initial P(fg) ≈ 0.01 (RetinaNet paper)
        prior = 0.01
        bias_value = -math.log((1 - prior) / prior)
        self.cls_out = _conv3(
            channels, num_anchors * num_classes, rngs,
            bias_init=nnx.initializers.constant(bias_value),
        )
        self.box_out = _conv3(channels, num_anchors * 4, rngs)
        self.num_classes = num_classes
        self.num_anchors = num_anchors

    def __call__(self, feats):
        cls_all, box_all = [], []
        for f in feats:
            c = f
            for conv in self.cls_tower:
                c = nnx.relu(conv(c))
            cls = self.cls_out(c)
            b = f
            for conv in self.box_tower:
                b = nnx.relu(conv(b))
            box = self.box_out(b)
            n = f.shape[0]
            cls_all.append(cls.reshape(n, -1, self.num_classes))
            box_all.append(box.reshape(n, -1, 4))
        return jnp.concatenate(cls_all, 1), jnp.concatenate(box_all, 1)


class RetinaNet(nnx.Module):
    """RetinaNet with a ResNet-50-FPN backbone.

    ``__call__(images)`` → (cls_logits (B, A, K), box_deltas (B, A, 4)).
    ``loss(images, gt_boxes, gt_labels, gt_valid)`` → (total, aux dict),
    with GT padded to a fixed ``max_boxes`` and masked by ``gt_valid`` —
    static shapes end to end.
    """

    def __init__(
        self,
        *,
        num_classes: int = 80,
        image_size: tuple[int, int] = (512, 512),
        fpn_channels: int = 256,
        backbone: ResNet | None = None,
        rngs: nnx.Rngs,
    ):
        if backbone is None:
            backbone = ResNet(
                Bottleneck, (3, 4, 6, 3), num_classes=1, rngs=rngs
            )
        self.backbone = backbone
        dims = (
            backbone.feature_dim // 4,   # C3
            backbone.feature_dim // 2,   # C4
            backbone.feature_dim,        # C5
        )
        self.fpn = FPN(dims, fpn_channels, rngs)
        self.head = RetinaHead(fpn_channels, num_anchors=9,
                               num_classes=num_classes, rngs=rngs)
        self.num_classes = num_classes
        self.image_size = image_size
        # static anchors for the configured image size (A, 4)
        self.anchors = nnx.Variable(det.retinanet_anchors(image_size))

    def __call__(self, images: jax.Array):
        feats = self.backbone.features(images)  # C2..C5
        p = self.fpn(feats[1], feats[2], feats[3])
        return self.head(p)

    def loss(self, images, gt_boxes, gt_labels, gt_valid):
        """Focal classification + smooth-L1 box loss, normalized by the
        number of foreground anchors (RetinaNet convention)."""
        cls_logits, box_deltas = self(images)
        anchors = self.anchors[...]

        def one_image(logits, deltas, boxes, labels, valid):
            matched, _ = det.match_anchors(anchors, boxes, valid)
            fg = matched >= 0
            ignore = matched == -2
            # classification targets: one-hot of matched GT class, zeros for bg
            safe = jnp.clip(matched, 0)
            cls_t = jax.nn.one_hot(labels[safe], self.num_classes) * fg[:, None]
            cls_loss = det.sigmoid_focal_loss(logits, cls_t)
            cls_loss = jnp.where(ignore[:, None], 0.0, cls_loss).sum()
            # box targets for fg anchors
            box_t = det.box_encode(boxes[safe], anchors)
            box_loss = det.smooth_l1(deltas, box_t).sum(-1)
            box_loss = jnp.where(fg, box_loss, 0.0).sum()
            n_fg = jnp.maximum(fg.sum(), 1)
            return cls_loss / n_fg, box_loss / n_fg

        cls_l, box_l = jax.vmap(one_image)(
            cls_logits, box_deltas, gt_boxes, gt_labels, gt_valid
        )
        total = cls_l.mean() + box_l.mean()
        return total, {"cls_loss": cls_l.mean(), "box_loss": box_l.mean()}

    def decode(self, images, *, score_thresh=0.05, top_k=100):
        """Inference: decode top-k scoring boxes per image (static top-k;
        full NMS is a post-process on host for eval)."""
        cls_logits, box_deltas = self(images)
        anchors = self.anchors[...]
        scores = jax.nn.sigmoid(cls_logits)  # (B, A, K)
        best_score = scores.max(-1)
        best_class = scores.argmax(-1)
        k = min(top_k, best_score.shape[1])
        top_scores, top_idx = jax.lax.top_k(best_score, k)
        boxes = det.box_decode(
            jnp.take_along_axis(box_deltas, top_idx[..., None], axis=1),
            anchors[top_idx],
        )
        classes = jnp.take_along_axis(best_class, top_idx, axis=1)
        keep = top_scores >= score_thresh
        return boxes, top_scores, classes, keep


def retinanet_r50_fpn(**kw) -> RetinaNet:
    return RetinaNet(**kw)
