"""Minimal transformer LM composing the sequence-parallel primitives.

The reference repo has no attention or sequence models (SURVEY §5.7);
this family exists so the framework's long-context support is usable as
a *model*, not just an op: the same forward runs dense on one device or
sequence-sharded over a ``seq`` mesh axis (ring or Ulysses attention),
with bit-compatible results — pinned in ``tests/test_transformer.py``.

Functional style (params as a pytree, pure apply) to match the
shard_map-level parallel primitives; pre-LN blocks, learned positional
embeddings, weight-tied output head. Layers are stacked into leading-
axis pytrees and applied with ``lax.scan`` so compile size is O(1) in
depth.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpu_syncbn.compat import axis_size as _compat_axis_size
from tpu_syncbn.parallel.sequence import (
    _single_device_attention,
    ring_attention,
    ulysses_attention,
)


def init_transformer_lm(
    rng: jax.Array,
    *,
    vocab: int,
    d_model: int,
    n_heads: int,
    n_layers: int,
    d_ff: int,
    max_len: int,
    dtype=jnp.float32,
):
    """Parameter pytree for :func:`transformer_lm`. Embedding is tied to
    the output head."""
    if d_model % n_heads:
        raise ValueError(f"d_model {d_model} % n_heads {n_heads} != 0")
    # exactly the keys consumed: embed, pos, and 4 matrices per layer
    # (the LN scales init to ones)
    k = iter(jax.random.split(rng, 2 + 4 * n_layers))

    def dense(key, shape, scale=None):
        scale = scale if scale is not None else shape[0] ** -0.5
        return (jax.random.normal(key, shape) * scale).astype(dtype)

    def stack(maker):
        return jnp.stack([maker(next(k)) for _ in range(n_layers)])

    return {
        "embed": dense(next(k), (vocab, d_model), scale=0.02),
        "pos": dense(next(k), (max_len, d_model), scale=0.02),
        "blocks": {
            "ln1_scale": jnp.ones((n_layers, d_model), dtype),
            "ln2_scale": jnp.ones((n_layers, d_model), dtype),
            "wqkv": stack(lambda key: dense(key, (d_model, 3 * d_model))),
            "wo": stack(lambda key: dense(key, (d_model, d_model))),
            "w1": stack(lambda key: dense(key, (d_model, d_ff))),
            "w2": stack(lambda key: dense(key, (d_ff, d_model))),
        },
        "ln_f_scale": jnp.ones((d_model,), dtype),
    }


def _rms_norm(x, scale):
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (y * scale).astype(x.dtype)


def _attend(q, k, v, impl: Optional[str], axis_name: Optional[str],
            local_impl: Optional[str] = None, local_backward: str = "xla"):
    if impl in ("flash", "flash_pallas_bwd"):
        # fused Pallas kernel over the FULL sequence — the dense
        # counterpart of the SP impls; opt-in pending hardware timing
        # (the ops.batch_norm evidence-gating stance). The _pallas_bwd
        # variant also routes the VJP through the fused two-kernel
        # Pallas backward (whole attention fwd+bwd on the MXU path).
        if axis_name is not None:
            raise ValueError(
                f"attn_impl={impl!r} is the dense single-device kernel; it "
                "would silently attend only the local shard under a "
                "sequence-sharded axis. Use attn_impl='ring'/'ulysses' "
                f"with axis_name, or {impl!r} with axis_name=None."
            )
        from tpu_syncbn.ops.pallas_attention import flash_attention

        backward = "pallas" if impl == "flash_pallas_bwd" else "xla"
        return flash_attention(q, k, v, causal=True, backward=backward)
    if impl is None or axis_name is None:
        return _single_device_attention(q, k, v, causal=True, scale=None)
    if impl == "ring":
        return ring_attention(q, k, v, axis_name, causal=True)
    if impl == "ulysses":
        return ulysses_attention(
            q, k, v, axis_name, causal=True,
            local_impl=local_impl, local_backward=local_backward,
        )
    if impl == "ring_zigzag":
        raise ValueError(
            "ring_zigzag is not supported at the LM layer: it requires the "
            "token stream, position embeddings, and next-token labels to "
            "all use the zigzag chunk order, which transformer_lm's "
            "contiguous pos_offset plumbing does not provide. Use "
            "parallel.ring_attention_zigzag / "
            "sharded_self_attention(impl='ring_zigzag') at the attention "
            "level, or attn_impl='ring' here."
        )
    raise ValueError(f"unknown attention impl {impl!r}")


def transformer_lm(
    params,
    tokens: jax.Array,
    *,
    n_heads: int,
    attn_impl: Optional[str] = None,
    axis_name: Optional[str] = None,
    pos_offset: Optional[jax.Array] = None,
    local_impl: Optional[str] = None,
    local_backward: str = "xla",
) -> jax.Array:
    """Causal LM forward: ``tokens`` (B, L) int32 → logits (B, L, vocab).

    Dense by default. Inside a ``shard_map`` over a ``seq`` axis, pass
    ``attn_impl="ring"`` (or ``"ulysses"``) and the axis name; ``tokens``
    is then the local sequence shard and ``pos_offset`` defaults to
    ``axis_index * L_local`` so positional embeddings line up with the
    global positions — attention is the only cross-shard op in a
    transformer, so everything else needs no change. ``n_heads`` is
    static (it shapes the reshape), so it rides as a kwarg, not a param
    leaf. ``local_impl``/``local_backward`` forward to
    ``ulysses_attention`` (Ulysses only): ``local_impl="flash"`` runs
    the local full-sequence attention through the fused Pallas kernel,
    ``local_backward="pallas"`` also its fused backward.
    """
    if local_impl is not None or local_backward != "xla":
        # the sharded-Ulysses path is the only consumer; anything else
        # (ring, dense, or ulysses WITHOUT an axis — which _attend
        # degrades to the single-device oracle) would silently drop the
        # requested kernel — same contract as sharded_self_attention
        if attn_impl != "ulysses" or axis_name is None:
            raise ValueError(
                "local_impl/local_backward apply to attn_impl='ulysses' "
                f"with an axis_name only, got attn_impl={attn_impl!r}, "
                f"axis_name={axis_name!r}"
            )
    b, l = tokens.shape
    max_len = params["pos"].shape[0]
    if pos_offset is None:
        # dynamic_slice CLAMPS an out-of-range start, which would silently
        # reuse trailing positions on far shards — check at trace time
        # (axis_size is static) instead
        n_shards = 1 if axis_name is None else _compat_axis_size(axis_name)
        if n_shards * l > max_len:
            raise ValueError(
                f"global sequence {n_shards * l} exceeds max_len {max_len}"
            )
        pos_offset = (
            jnp.int32(0) if axis_name is None else lax.axis_index(axis_name) * l
        )

    x = params["embed"][tokens]
    x = x + lax.dynamic_slice_in_dim(params["pos"], pos_offset, l)

    d_model = x.shape[-1]
    dh = d_model // n_heads

    def block(x, p):
        h = _rms_norm(x, p["ln1_scale"])
        qkv = h @ p["wqkv"]
        q, k_, v = jnp.split(qkv, 3, axis=-1)
        shp = (b, l, n_heads, dh)
        o = _attend(
            q.reshape(shp), k_.reshape(shp), v.reshape(shp),
            attn_impl, axis_name, local_impl, local_backward,
        )
        x = x + o.reshape(b, l, d_model) @ p["wo"]
        h = _rms_norm(x, p["ln2_scale"])
        x = x + jax.nn.gelu(h @ p["w1"]) @ p["w2"]
        return x, None

    x, _ = lax.scan(block, x, params["blocks"])
    x = _rms_norm(x, params["ln_f_scale"])
    return (x @ params["embed"].T).astype(jnp.float32)
