"""DCGAN / SNGAN (nnx, NHWC) — the GAN-stability SyncBN capability config
(BASELINE.json: "DCGAN / SNGAN CIFAR-10 with SyncBN in G and D"; GANs are
the second workload the reference's recipe names as needing SyncBN,
``README.md:3``).

Architectures follow the DCGAN paper / pytorch-examples dcgan layout
(32×32): generator of stride-2 transposed convs with BN+ReLU and tanh
output; discriminator of stride-2 convs with BN (SNGAN: spectral-norm
convs) + LeakyReLU. BatchNorm modules are the framework's own, so
``convert_sync_batchnorm`` makes both networks sync their statistics
across replicas — the per-chip GAN batches that motivate SyncBN are tiny.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import nnx

from tpu_syncbn import compat

from tpu_syncbn.nn import BatchNorm2d

_g_init = nnx.initializers.normal(0.02)  # DCGAN init


class SNConv(nnx.Module):
    """Conv with spectral normalization (SNGAN): one power-iteration step
    per training forward, ``u`` carried as framework state with
    torch.nn.utils.spectral_norm's buffer semantics — updated in train
    mode, frozen in eval.

    The mode flag is named ``use_running_average`` so nnx's standard
    ``model.train()``/``model.eval()`` attribute propagation reaches it
    (the same contract as BatchNorm); ``True`` freezes the power-iteration
    buffer.
    """

    def __init__(self, cin, cout, kernel, stride, rngs, *, padding="SAME"):
        self.conv = nnx.Conv(
            cin, cout, kernel, strides=stride, padding=padding,
            kernel_init=_g_init, rngs=rngs,
        )
        self.u = nnx.BatchStat(
            jax.random.normal(rngs.params(), (cout,)) / jnp.sqrt(cout)
        )
        self.use_running_average = False

    def __call__(self, x):
        kernel = self.conv.kernel[...]
        w2 = kernel.reshape(-1, kernel.shape[-1])  # (kh*kw*cin, cout)
        # power iteration on a detached view: u and v carry no gradient...
        w2_sg = jax.lax.stop_gradient(w2)
        u = self.u[...]
        v = w2_sg @ u
        v = v / (jnp.linalg.norm(v) + 1e-12)
        u_new = w2_sg.T @ v
        u_new = u_new / (jnp.linalg.norm(u_new) + 1e-12)
        if not self.use_running_average:
            self.u.value = u_new
        # ...but sigma = v^T W u keeps the gradient path THROUGH W, exactly
        # torch.nn.utils.spectral_norm (only u/v are detached there)
        sigma = v @ w2 @ u_new
        w_sn = kernel / sigma
        y = jax.lax.conv_general_dilated(
            x, w_sn,
            window_strides=self.conv.strides,
            padding=self.conv.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.conv.use_bias:
            y = y + self.conv.bias[...]
        return y


class DCGANGenerator(nnx.Module):
    """latent (B, Z) → image (B, 32, 32, 3) in [-1, 1]."""

    def __init__(self, *, latent_dim=128, width=256, rngs: nnx.Rngs):
        self.latent_dim = latent_dim
        self.fc = nnx.Linear(latent_dim, 4 * 4 * width, kernel_init=_g_init, rngs=rngs)
        self.bn0 = BatchNorm2d(width)
        self.deconvs = compat.nnx_list([
            nnx.ConvTranspose(width, width // 2, (4, 4), strides=(2, 2),
                              padding="SAME", kernel_init=_g_init, rngs=rngs),
            nnx.ConvTranspose(width // 2, width // 4, (4, 4), strides=(2, 2),
                              padding="SAME", kernel_init=_g_init, rngs=rngs),
            nnx.ConvTranspose(width // 4, width // 4, (4, 4), strides=(2, 2),
                              padding="SAME", kernel_init=_g_init, rngs=rngs),
        ])
        self.bns = compat.nnx_list([
            BatchNorm2d(width // 2),
            BatchNorm2d(width // 4),
            BatchNorm2d(width // 4),
        ])
        self.out = nnx.Conv(width // 4, 3, (3, 3), padding="SAME",
                            kernel_init=_g_init, rngs=rngs)
        self.width = width

    def __call__(self, z):
        x = self.fc(z).reshape(z.shape[0], 4, 4, self.width)
        x = nnx.relu(self.bn0(x))
        for deconv, bn in zip(self.deconvs, self.bns):
            x = nnx.relu(bn(deconv(x)))
        return jnp.tanh(self.out(x))


class DCGANDiscriminator(nnx.Module):
    """image (B, 32, 32, 3) → logit (B,). BN on all but the first conv
    (DCGAN recipe)."""

    def __init__(self, *, width=64, rngs: nnx.Rngs):
        self.conv1 = nnx.Conv(3, width, (4, 4), strides=(2, 2), padding="SAME",
                              kernel_init=_g_init, rngs=rngs)
        self.conv2 = nnx.Conv(width, width * 2, (4, 4), strides=(2, 2),
                              padding="SAME", kernel_init=_g_init, rngs=rngs)
        self.bn2 = BatchNorm2d(width * 2)
        self.conv3 = nnx.Conv(width * 2, width * 4, (4, 4), strides=(2, 2),
                              padding="SAME", kernel_init=_g_init, rngs=rngs)
        self.bn3 = BatchNorm2d(width * 4)
        self.fc = nnx.Linear(width * 4 * 4 * 4, 1, kernel_init=_g_init, rngs=rngs)

    def __call__(self, x):
        return self.fc(self._trunk(x).reshape(x.shape[0], -1))[:, 0]

    def _trunk(self, x):
        a = 0.2
        x = nnx.leaky_relu(self.conv1(x), a)
        x = nnx.leaky_relu(self.bn2(self.conv2(x)), a)
        return nnx.leaky_relu(self.bn3(self.conv3(x)), a)

    def features(self, x):
        """Spatially-pooled penultimate activations, (B, 4*width) — a
        fixed feature space for distributional sample-quality metrics
        (``utils.frechet_distance``)."""
        return self._trunk(x).mean(axis=(1, 2))


class SNGANDiscriminator(nnx.Module):
    """Spectral-norm discriminator (SNGAN); BN optional (SNGAN typically
    drops BN in D — set use_bn=True to exercise SyncBN in D too, matching
    the capability config's 'SyncBN in G and D')."""

    def __init__(self, *, width=64, use_bn=True, rngs: nnx.Rngs):
        self.conv1 = SNConv(3, width, (4, 4), (2, 2), rngs)
        self.conv2 = SNConv(width, width * 2, (4, 4), (2, 2), rngs)
        self.bn2 = BatchNorm2d(width * 2) if use_bn else None
        self.conv3 = SNConv(width * 2, width * 4, (4, 4), (2, 2), rngs)
        self.bn3 = BatchNorm2d(width * 4) if use_bn else None
        self.fc = nnx.Linear(width * 4 * 4 * 4, 1, kernel_init=_g_init, rngs=rngs)

    def __call__(self, x):
        return self.fc(self._trunk(x).reshape(x.shape[0], -1))[:, 0]

    def _trunk(self, x):
        a = 0.1
        x = nnx.leaky_relu(self.conv1(x), a)
        x = self.conv2(x)
        if self.bn2 is not None:
            x = self.bn2(x)
        x = nnx.leaky_relu(x, a)
        x = self.conv3(x)
        if self.bn3 is not None:
            x = self.bn3(x)
        return nnx.leaky_relu(x, a)

    def features(self, x):
        """Spatially-pooled penultimate activations, (B, 4*width) — see
        ``DCGANDiscriminator.features``."""
        return self._trunk(x).mean(axis=(1, 2))


# -- losses ---------------------------------------------------------------


def bce_gan_losses(real_logits, fake_logits):
    """DCGAN losses: D maximizes log D(x) + log(1-D(G(z))); G maximizes
    log D(G(z)) (non-saturating)."""
    import optax

    ones = jnp.ones_like(real_logits)
    zeros = jnp.zeros_like(fake_logits)
    d_loss = (
        optax.sigmoid_binary_cross_entropy(real_logits, ones).mean()
        + optax.sigmoid_binary_cross_entropy(fake_logits, zeros).mean()
    )
    g_loss = optax.sigmoid_binary_cross_entropy(fake_logits, ones).mean()
    return d_loss, g_loss


def hinge_gan_losses(real_logits, fake_logits):
    """SNGAN hinge losses."""
    d_loss = (
        jnp.maximum(0.0, 1.0 - real_logits).mean()
        + jnp.maximum(0.0, 1.0 + fake_logits).mean()
    )
    g_loss = -fake_logits.mean()
    return d_loss, g_loss
