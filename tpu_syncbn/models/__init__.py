"""Model zoo: the architectures named by the reference's capability configs
(ResNet-18/50, RetinaNet-R50-FPN, DCGAN/SNGAN — BASELINE.json), plus the
transformer LM that exercises the long-context path."""

from tpu_syncbn.models import detection, gan, transformer
from tpu_syncbn.models.transformer import init_transformer_lm, transformer_lm
from tpu_syncbn.models.gan import (
    DCGANGenerator,
    DCGANDiscriminator,
    SNGANDiscriminator,
    SNConv,
)
from tpu_syncbn.models.retinanet import RetinaNet, FPN, RetinaHead, retinanet_r50_fpn
from tpu_syncbn.models.resnet import (
    ResNet,
    BasicBlock,
    Bottleneck,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    RESNETS,
)

__all__ = [
    "gan",
    "DCGANGenerator",
    "DCGANDiscriminator",
    "SNGANDiscriminator",
    "SNConv",
    "detection",
    "RetinaNet",
    "FPN",
    "RetinaHead",
    "retinanet_r50_fpn",
    "ResNet",
    "BasicBlock",
    "Bottleneck",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
    "RESNETS",
    "transformer",
    "init_transformer_lm",
    "transformer_lm",
]
