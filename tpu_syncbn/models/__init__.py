"""Model zoo: the architectures named by the reference's capability configs
(ResNet-18/50, RetinaNet-R50-FPN, DCGAN/SNGAN — BASELINE.json)."""

from tpu_syncbn.models.resnet import (
    ResNet,
    BasicBlock,
    Bottleneck,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    RESNETS,
)

__all__ = [
    "ResNet",
    "BasicBlock",
    "Bottleneck",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
    "RESNETS",
]
