"""BatchNorm / SyncBatchNorm modules (flax nnx) with the reference's drop-in
contract.

``SyncBatchNorm`` reproduces the capability of ``torch.nn.SyncBatchNorm``
(reference ``README.md:40-45``; implementation
``[torch] nn/modules/batchnorm.py:650-887``): in training mode, per-channel
batch statistics are reduced across every replica on the ``data`` mesh axis
before normalizing, so each replica normalizes against the *global* batch.
In eval mode (or when no mesh axis is active) it falls back to plain BN with
zero collectives — the reference's need_sync/fallback split
(``[torch] nn/modules/batchnorm.py:837-873``).

Differences from torch, by design (TPU-first):

* layout is channel-last (NHWC) by default — the TPU lane dimension is the
  channel; ``channel_axis`` covers NCHW;
* there is no process-group object: the replica group is a mesh axis name,
  and sync happens whenever the module runs inside ``shard_map``/``pjit``
  with that axis in scope (the trainer arranges this);
* running-stat mutation is an nnx ``BatchStat`` variable update, which the
  compiled step threads functionally (SURVEY §7 "state under jit").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import nnx

from tpu_syncbn.ops import batch_norm as bn_ops
from tpu_syncbn.parallel.collectives import (
    check_compress_mode,
    normalize_group_spec,
)
from tpu_syncbn.runtime.distributed import DATA_AXIS


def _axis_in_scope(axis_name: str) -> bool:
    """True when ``axis_name`` is a live named mesh axis at trace time (i.e.
    we are inside shard_map/pmap over it) — the analogue of the reference's
    ``need_sync = training and dist.is_initialized() and world > 1`` check
    (``[torch] nn/modules/batchnorm.py:837-860``)."""
    from tpu_syncbn import compat

    try:
        compat.axis_size(axis_name)
        return True
    except (NameError, KeyError):
        return False


class BatchNorm(nnx.Module):
    """Plain batch normalization over the batch (+spatial) axes.

    Mirrors ``torch.nn.BatchNorm1d/2d/3d`` semantics
    (``[torch] nn/modules/batchnorm.py``): biased variance for
    normalization, unbiased for the running buffer, ``momentum=None``
    cumulative averaging, optional affine, optional running stats.

    Mode: ``use_running_average`` is flipped by ``nnx``'s standard
    ``model.train()`` / ``model.eval()`` attribute propagation.
    """

    def __init__(
        self,
        num_features: int,
        *,
        eps: float = 1e-5,
        momentum: float | None = 0.1,
        affine: bool = True,
        track_running_stats: bool = True,
        channel_axis: int = -1,
        axis_name: str | None = None,
        group_size: int | tuple | None = None,
        stats_compress: str = "none",
        dtype: jnp.dtype = jnp.float32,
        rngs: nnx.Rngs | None = None,  # unused; accepted for nnx idiom
    ):
        if (
            axis_name is not None
            or group_size is not None
            or stats_compress != "none"
        ) and not isinstance(self, SyncBatchNorm):
            # Plain BN never syncs (that per-replica behavior is the bug
            # the reference exists to fix, README.md:3); accepting sync
            # parameters here and ignoring them would silently reintroduce it.
            raise ValueError(
                "plain BatchNorm does not sync across replicas; use "
                "SyncBatchNorm (or convert_sync_batchnorm) for "
                f"axis_name={axis_name!r} / group_size={group_size!r} / "
                f"stats_compress={stats_compress!r}"
            )
        self.num_features = num_features
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.track_running_stats = track_running_stats
        self.channel_axis = channel_axis
        self.axis_name = axis_name
        # int stays int (contiguous groups); an explicit rank partition
        # (torch's arbitrary process_group sets) becomes hashable nested
        # tuples, stable under jit caching; membership is validated
        # against the axis size at trace time (psum_in_groups)
        self.group_size = normalize_group_spec(group_size)
        #: wire dtype of the cross-replica moment reduction — stats stay
        #: exact fp32 unless EXPLICITLY opted into a lossy mode,
        #: independently of any gradient compression the trainer applies
        #: (the count census always stays fp32 either way —
        #: collectives.reduce_moments)
        self.stats_compress = check_compress_mode(stats_compress)
        self.use_running_average = False
        if affine:
            # torch init: weight=1, bias=0 ([torch] nn/modules/batchnorm.py reset_parameters)
            self.weight = nnx.Param(jnp.ones((num_features,), dtype))
            self.bias = nnx.Param(jnp.zeros((num_features,), dtype))
        else:
            self.weight = None
            self.bias = None
        if track_running_stats:
            self.running_mean = nnx.BatchStat(jnp.zeros((num_features,), jnp.float32))
            self.running_var = nnx.BatchStat(jnp.ones((num_features,), jnp.float32))
            self.num_batches_tracked = nnx.BatchStat(jnp.zeros((), jnp.int32))
        else:
            self.running_mean = None
            self.running_var = None
            self.num_batches_tracked = None

    def _check_input(self, x: jax.Array) -> None:
        c = x.shape[self.channel_axis]
        if c != self.num_features:
            raise ValueError(
                f"expected {self.num_features} channels on axis "
                f"{self.channel_axis}, got shape {x.shape}"
            )

    def _sync_axis(self) -> str | None:
        """The mesh axis to sync over, or None for local stats. Plain
        BatchNorm never syncs (torch BN under DDP keeps per-replica stats —
        the exact behavior the reference exists to fix, ``README.md:3``)."""
        return None

    def __call__(self, x: jax.Array, *, mask: jax.Array | None = None) -> jax.Array:
        self._check_input(x)
        w = self.weight[...] if self.weight is not None else None
        b = self.bias[...] if self.bias is not None else None

        use_running = self.use_running_average and self.track_running_stats
        if use_running:
            # eval fallback: zero collectives ([torch] batchnorm.py:863-873)
            return bn_ops.batch_norm_inference(
                x,
                self.running_mean[...],
                self.running_var[...],
                w,
                b,
                eps=self.eps,
                channel_axis=self.channel_axis,
            )

        rm = self.running_mean[...] if self.track_running_stats else None
        rv = self.running_var[...] if self.track_running_stats else None
        nbt = self.num_batches_tracked[...] if self.track_running_stats else None
        y, (new_rm, new_rv, new_nbt) = bn_ops.batch_norm_train(
            x,
            rm,
            rv,
            nbt,
            w,
            b,
            momentum=self.momentum,
            eps=self.eps,
            channel_axis=self.channel_axis,
            axis_name=self._sync_axis(),
            group_size=self.group_size if self._sync_axis() else None,
            stats_compress=(
                self.stats_compress if self._sync_axis() else "none"
            ),
            mask=mask,
        )
        if self.track_running_stats:
            # .value assignment (not var[...] = x): portable across
            # flax versions whose Variable.__setitem__ writes through to
            # the (immutable) jax array instead of rebinding it
            self.running_mean.value = new_rm
            self.running_var.value = new_rv
            self.num_batches_tracked.value = new_nbt
        return y


class BatchNorm1d(BatchNorm):
    """Rank-2/3 inputs (N, C) or (N, L, C) — torch.nn.BatchNorm1d analogue."""

    def _check_input(self, x):
        if x.ndim not in (2, 3):
            raise ValueError(f"BatchNorm1d expects 2D/3D input, got {x.ndim}D")
        super()._check_input(x)


class BatchNorm2d(BatchNorm):
    """Rank-4 inputs (N, H, W, C) — torch.nn.BatchNorm2d analogue (NHWC)."""

    def _check_input(self, x):
        if x.ndim != 4:
            raise ValueError(f"BatchNorm2d expects 4D input, got {x.ndim}D")
        super()._check_input(x)


class BatchNorm3d(BatchNorm):
    """Rank-5 inputs (N, D, H, W, C) — torch.nn.BatchNorm3d analogue."""

    def _check_input(self, x):
        if x.ndim != 5:
            raise ValueError(f"BatchNorm3d expects 5D input, got {x.ndim}D")
        super()._check_input(x)


class SyncBatchNorm(BatchNorm):
    """Cross-replica synchronized BatchNorm — ``torch.nn.SyncBatchNorm``
    rebuilt TPU-native (reference ``README.md:40-45``).

    When training inside a mesh context that carries ``self.axis_name``
    (the trainer's shard_map over the ``data`` axis), per-channel moments
    are reduced across all replicas — or within replica subgroups, the
    torch ``process_group`` scoping (``[torch] nn/modules/batchnorm.py:706``):
    ``group_size`` takes an int (contiguous, topology-shaped subgroups)
    or an explicit partition of ranks like ``((0, 3, 5), (1, 2, 4, 6, 7))``
    for torch's arbitrary rank sets — with one fused psum
    (see ops.batch_norm.sync_moments). Outside any mesh context — eval
    mode, single-replica debugging, world size 1 — it degrades to plain BN
    exactly like the reference's fallback
    (``[torch] nn/modules/batchnorm.py:837-873``).
    """

    def __init__(self, num_features: int, *, axis_name: str = DATA_AXIS, **kw):
        super().__init__(num_features, axis_name=axis_name, **kw)

    @classmethod
    def convert_sync_batchnorm(
        cls, module, axis_name: str = DATA_AXIS,
        group_size: int | tuple | None = None,
        stats_compress: str = "none",
    ):
        """Drop-in spelling parity with
        ``torch.nn.SyncBatchNorm.convert_sync_batchnorm(module,
        process_group)`` (``[torch] nn/modules/batchnorm.py:889``);
        delegates to :func:`tpu_syncbn.nn.convert_sync_batchnorm`."""
        from tpu_syncbn.nn.convert import convert_sync_batchnorm

        return convert_sync_batchnorm(
            module, axis_name, group_size, stats_compress
        )

    def _sync_axis(self) -> str | None:
        # torch's need_sync requires self.training ([torch] nn/modules/
        # batchnorm.py:837-860): eval mode never syncs, even when
        # track_running_stats=False puts eval on the batch-stats path.
        if (
            not self.use_running_average
            and self.axis_name is not None
            and _axis_in_scope(self.axis_name)
        ):
            return self.axis_name
        return None
