"""``convert_sync_batchnorm`` — recursive module-tree rewrite.

The TPU-native equivalent of
``torch.nn.SyncBatchNorm.convert_sync_batchnorm`` (reference
``README.md:40-45``; implementation
``[torch] nn/modules/batchnorm.py:889-951``): walk the module tree, replace
every :class:`~tpu_syncbn.nn.BatchNorm` (and subclasses) with a
:class:`~tpu_syncbn.nn.SyncBatchNorm` that *shares* the original's
parameters and running buffers (weight/bias/running_mean/running_var/
num_batches_tracked are carried over by reference, exactly as torch carries
them over at ``:927-937``), preserving eps/momentum/affine/track flags and
the train/eval mode flag.

Because nnx modules are mutable Python objects (like torch modules), this
is a true drop-in transform: the returned tree is the same object graph
with BN nodes swapped, so optimizer state keyed on the other parameters is
untouched.
"""

from __future__ import annotations

from flax import nnx

from tpu_syncbn.nn.normalization import BatchNorm, SyncBatchNorm
from tpu_syncbn.parallel.collectives import (
    check_compress_mode,
    normalize_group_spec,
)
from tpu_syncbn.runtime.distributed import DATA_AXIS


def _convert_one(
    bn: BatchNorm, axis_name: str, group_size=None,
    stats_compress: str = "none",
) -> SyncBatchNorm:
    out = SyncBatchNorm(
        bn.num_features,
        eps=bn.eps,
        momentum=bn.momentum,
        affine=bn.affine,
        track_running_stats=bn.track_running_stats,
        channel_axis=bn.channel_axis,
        axis_name=axis_name,
        group_size=group_size,
        stats_compress=stats_compress,
    )
    # Share (not copy) variables — the torch converter moves the same
    # Parameter/buffer objects onto the new module
    # ([torch] nn/modules/batchnorm.py:927-937).
    out.weight = bn.weight
    out.bias = bn.bias
    out.running_mean = bn.running_mean
    out.running_var = bn.running_var
    out.num_batches_tracked = bn.num_batches_tracked
    out.use_running_average = bn.use_running_average
    return out


def _swap_in_container(value, axis_name: str, group_size=None,
                       stats_compress: str = "none"):
    """Swap BN→SyncBN inside ``value``; returns ``value`` itself (same
    object identity) when nothing needed converting."""
    if isinstance(value, SyncBatchNorm):
        # torch re-converts SyncBatchNorm too (it subclasses _BatchNorm),
        # uniformly applying the given process_group — update the scope
        # in place rather than leaving a mixed-scope model silently.
        value.axis_name = axis_name
        value.group_size = group_size
        value.stats_compress = stats_compress
        return value
    if isinstance(value, BatchNorm):
        return _convert_one(value, axis_name, group_size, stats_compress)
    if isinstance(value, (list, tuple)):
        new = [_swap_in_container(v, axis_name, group_size,
                                  stats_compress) for v in value]
        if all(a is b for a, b in zip(new, value)):
            return value
        if isinstance(value, tuple) and hasattr(value, "_fields"):  # namedtuple
            return type(value)(*new)
        return type(value)(new)
    if isinstance(value, dict):
        new = {k: _swap_in_container(v, axis_name, group_size,
                                     stats_compress)
               for k, v in value.items()}
        if all(new[k] is value[k] for k in value):
            return value
        return new
    return value


def convert_sync_batchnorm(
    module: nnx.Module, axis_name: str = DATA_AXIS,
    group_size: int | tuple | None = None,
    stats_compress: str = "none",
):
    """Recursively replace BatchNorm modules with SyncBatchNorm.

    Drop-in contract of ``[torch] nn/modules/batchnorm.py:889-951``:
    parameters and buffers are shared by reference; config and mode flags
    preserved. Returns the (possibly new) root; inner modules are rewritten
    in place. ``axis_name`` + ``group_size`` play the role of torch's
    ``process_group`` argument: the mesh axis the statistics sync over
    and (optionally) which replicas sync together — an int for
    contiguous subgroups of that size, or an explicit rank partition
    like ``((0, 3, 5), (1, 2, 4, 6, 7))`` for torch's arbitrary rank
    sets. ``stats_compress`` opts the moment reduction into a lossy wire
    dtype (``"bf16"``/``"int8"``; docs/PERFORMANCE.md "Compressed
    collectives") — the safe default keeps stats exact fp32, independent
    of any ``DataParallel(compress=...)`` gradient compression.
    """
    # same canonical form BatchNorm.__init__ applies — the in-place
    # rewrite path (value.group_size = ...) bypasses init
    group_size = normalize_group_spec(group_size)
    check_compress_mode(stats_compress)
    if isinstance(module, BatchNorm):
        return _swap_in_container(module, axis_name, group_size,
                                  stats_compress)
    seen = set()
    for _path, node in nnx.iter_graph(module):
        if not isinstance(node, nnx.Module) or id(node) in seen:
            continue
        seen.add(id(node))
        if isinstance(node, getattr(nnx, "List", ())):
            # flax without nnx.List registers plain Python lists as graph
            # nodes; those are rewritten through the owning module's
            # vars() walk below instead
            for i in range(len(node)):
                new = _swap_in_container(node[i], axis_name, group_size,
                                           stats_compress)
                if new is not node[i]:
                    node[i] = new
            continue
        if isinstance(node, getattr(nnx, "Dict", ())):
            for k in list(node):
                new = _swap_in_container(node[k], axis_name, group_size,
                                           stats_compress)
                if new is not node[k]:
                    node[k] = new
            continue
        for attr, value in list(vars(node).items()):
            # torch's converter replaces every named child regardless of
            # attribute name ([torch] batchnorm.py:939-941); only nnx's own
            # bookkeeping attribute is off-limits.
            if attr == "_object__state":
                continue
            new = _swap_in_container(value, axis_name, group_size,
                                     stats_compress)
            if new is not value:
                setattr(node, attr, new)
    return module
