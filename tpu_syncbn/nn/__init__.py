"""NN modules: BatchNorm family and the SyncBatchNorm conversion transform
(the reference's L3 model-wrapper layer, README.md:40-72)."""

from tpu_syncbn.nn.normalization import (
    BatchNorm,
    BatchNorm1d,
    BatchNorm2d,
    BatchNorm3d,
    SyncBatchNorm,
)
from tpu_syncbn.nn.convert import convert_sync_batchnorm

__all__ = [
    "BatchNorm",
    "BatchNorm1d",
    "BatchNorm2d",
    "BatchNorm3d",
    "SyncBatchNorm",
    "convert_sync_batchnorm",
]
