"""Open-loop load generation: the only way to see a server past
saturation.

A *closed-loop* client (``bench --serve``'s PR 5 sweep) waits for each
answer before sending the next request, so offered load self-limits at
the server's capacity — queueing collapse is unobservable by
construction. An *open-loop* generator submits on a fixed arrival
schedule regardless of completions, exactly like independent users: past
saturation the queue grows, deadlines start missing, and what separates
a robust server from a collapsing one is **goodput** (answers delivered
within their deadline) staying near capacity while p99 stays bounded and
the excess is *shed*, not queued (the ROADMAP item 4 acceptance regime).

Determinism contract (same as :mod:`tpu_syncbn.testing.faults`): arrival
schedules are derived from an explicit seed (``random.Random``
exponential gaps for Poisson, or an explicit trace of arrival times) —
a failing overload test reproduces bit-for-bit. Only the *schedule* is
seeded; observed latencies are measurements.

Usage::

    gen = OpenLoopLoadGen(batcher.submit, make_request=lambda i: x[i:i+1])
    report = gen.run(poisson_arrivals(rate_rps=200, duration_s=2.0,
                                      seed=0))
    report.goodput_rps, report.latency_p99_ms, report.shed_rate

``sweep()`` runs several offered-load levels and returns their reports —
the shape ``bench --serve``'s schema-pinned ``open_loop`` section is
built from.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Sequence

from tpu_syncbn.serve.admission import DeadlineExceededError, RejectedError

__all__ = [
    "poisson_arrivals",
    "trace_arrivals",
    "LoadReport",
    "OpenLoopLoadGen",
]


def poisson_arrivals(
    rate_rps: float, duration_s: float, *, seed: int = 0
) -> list[float]:
    """Relative arrival offsets (seconds from start) of a Poisson
    process at ``rate_rps`` over ``duration_s`` — exponential
    inter-arrival gaps from a seeded RNG, no wall-clock randomness."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0, got {duration_s}")
    rng = random.Random(seed)
    out: list[float] = []
    t = rng.expovariate(rate_rps)
    while t < duration_s:
        out.append(t)
        t += rng.expovariate(rate_rps)
    return out


def trace_arrivals(times: Sequence[float]) -> list[float]:
    """Validate an explicit arrival trace (relative offsets, seconds):
    sorted, non-negative — replayed production traffic or a handcrafted
    burst pattern."""
    out = [float(t) for t in times]
    if any(t < 0 for t in out):
        raise ValueError("arrival offsets must be >= 0")
    if out != sorted(out):
        raise ValueError("arrival offsets must be sorted ascending")
    return out


def _percentile(sorted_vals: list[float], q: float) -> float | None:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[int(idx)]


@dataclasses.dataclass
class LoadReport:
    """One open-loop level's measurements. ``offered`` counts scheduled
    arrivals; every request ends in exactly one of ``answered`` (in
    time), ``late`` (answered past deadline), ``shed`` (deadline policy
    failed it), ``rejected`` (backpressure/circuit/drain at submit or
    queue-fail), or ``errored`` (engine exception) — plus ``lost`` for
    anything unresolved at the collection timeout (should be 0)."""

    offered: int
    duration_s: float
    answered: int
    late: int
    shed: int
    rejected: int
    errored: int
    lost: int
    #: latency of EVERY answered request, late ones included — so the
    #: reported p99 is the honest client-visible tail, and "p99 stays
    #: bounded" is a claim about shedding policy, not bookkeeping
    latencies_s: list[float] = dataclasses.field(repr=False)

    @property
    def offered_rps(self) -> float:
        return self.offered / self.duration_s if self.duration_s else 0.0

    @property
    def goodput_rps(self) -> float:
        """In-deadline answers per second — the number that must stay
        near capacity past saturation."""
        return self.answered / self.duration_s if self.duration_s else 0.0

    @property
    def deadline_miss_rate(self) -> float:
        """(sheds + late answers) / offered."""
        return ((self.shed + self.late) / self.offered
                if self.offered else 0.0)

    @property
    def shed_rate(self) -> float:
        return self.shed / self.offered if self.offered else 0.0

    @property
    def reject_rate(self) -> float:
        return self.rejected / self.offered if self.offered else 0.0

    def latency_ms(self, q: float) -> float | None:
        """Latency percentile over every answered request (late
        included), in ms."""
        v = _percentile(sorted(self.latencies_s), q)
        return None if v is None else v * 1e3

    def summary(self) -> dict:
        """JSON-ready block (the bench ``open_loop`` level schema)."""
        p50 = self.latency_ms(0.50)
        p99 = self.latency_ms(0.99)
        return {
            "offered": self.offered,
            "offered_rps": round(self.offered_rps, 2),
            "duration_s": round(self.duration_s, 3),
            "answered": self.answered,
            "goodput_rps": round(self.goodput_rps, 2),
            "latency_p50_ms": round(p50, 3) if p50 is not None else None,
            "latency_p99_ms": round(p99, 3) if p99 is not None else None,
            "deadline_miss_rate": round(self.deadline_miss_rate, 4),
            "shed_rate": round(self.shed_rate, 4),
            "reject_rate": round(self.reject_rate, 4),
            "late": self.late,
            "shed": self.shed,
            "rejected": self.rejected,
            "errored": self.errored,
            "lost": self.lost,
        }


class OpenLoopLoadGen:
    """Drive ``submit`` (the batcher's, or any callable returning a
    ``concurrent.futures.Future``) on a fixed arrival schedule.

    ``make_request(i)`` builds the i-th request payload (default: the
    integer index — fine for stub engines). ``deadline_ms`` is threaded
    through to ``submit`` when given (the batcher's per-request
    override); the same value classifies answered-but-late responses.
    The generator never blocks on a response: completions are recorded
    by future callbacks, which is what makes the loop open."""

    def __init__(
        self,
        submit: Callable,
        *,
        make_request: Callable[[int], object] | None = None,
        deadline_ms: float | None = None,
    ):
        self._submit = submit
        self._make_request = (make_request if make_request is not None
                              else lambda i: i)
        self.deadline_ms = deadline_ms

    def run(
        self,
        arrivals: Sequence[float],
        *,
        collect_timeout_s: float = 60.0,
    ) -> LoadReport:
        """Submit one request per arrival offset, sleeping to hold the
        schedule (a late generator — host stall — submits immediately;
        offered load is never silently reduced). Blocks until every
        future resolves or ``collect_timeout_s`` passes, then reports."""
        arrivals = trace_arrivals(arrivals)
        lock = threading.Lock()
        latencies: list[float] = []
        counts = {"late": 0, "shed": 0, "rejected": 0, "errored": 0}
        outstanding = threading.Semaphore(0)
        resolved = [0]
        deadline_s = (None if self.deadline_ms is None
                      else self.deadline_ms / 1e3)

        def done(t_submit: float, fut) -> None:
            dt = time.monotonic() - t_submit
            try:
                fut.result()
            except DeadlineExceededError:
                kind = "shed"
            except RejectedError:
                kind = "rejected"
            except Exception:
                kind = "errored"
            else:
                kind = ("late" if deadline_s is not None and dt > deadline_s
                        else None)
            with lock:
                if kind is None or kind == "late":
                    latencies.append(dt)  # every answer counts in p99
                if kind is not None:
                    counts[kind] += 1
                resolved[0] += 1
            outstanding.release()

        t0 = time.monotonic()
        submitted = 0
        for i, offset in enumerate(arrivals):
            delay = (t0 + offset) - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            payload = self._make_request(i)
            t_submit = time.monotonic()
            try:
                if self.deadline_ms is not None:
                    fut = self._submit(payload, deadline_ms=self.deadline_ms)
                else:
                    fut = self._submit(payload)
            except RejectedError:
                with lock:
                    counts["rejected"] += 1
                    resolved[0] += 1
                outstanding.release()
            else:
                fut.add_done_callback(
                    lambda f, t=t_submit: done(t, f)
                )
            submitted += 1
        # the offered window ends with the last submit — rates are
        # per-window; the collection tail below must not dilute them
        duration = time.monotonic() - t0
        # collect: every arrival resolves exactly once (callback or
        # submit-time rejection); anything still pending at the timeout
        # is counted lost, never waited on forever
        end = time.monotonic() + collect_timeout_s
        collected = 0
        while collected < submitted:
            remaining = end - time.monotonic()
            if remaining <= 0 or not outstanding.acquire(timeout=remaining):
                break
            collected += 1
        with lock:
            return LoadReport(
                offered=submitted,
                duration_s=duration,
                answered=len(latencies) - counts["late"],
                late=counts["late"],
                shed=counts["shed"],
                rejected=counts["rejected"],
                errored=counts["errored"],
                lost=submitted - resolved[0],
                latencies_s=list(latencies),
            )

    def sweep(
        self,
        rates_rps: Sequence[float],
        *,
        duration_s: float = 1.0,
        seed: int = 0,
        collect_timeout_s: float = 60.0,
    ) -> list[LoadReport]:
        """One :meth:`run` per offered rate (each level's schedule
        seeded with ``seed + level index`` — distinct but reproducible
        arrival patterns), returned in order."""
        return [
            self.run(
                poisson_arrivals(r, duration_s, seed=seed + i),
                collect_timeout_s=collect_timeout_s,
            )
            for i, r in enumerate(rates_rps)
        ]
