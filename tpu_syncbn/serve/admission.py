"""Overload-aware admission: deadlines, EDF dispatch, load shedding,
and circuit breaking for the serving stack.

``bench --serve``'s closed-loop sweep (PR 5) can never push the batcher
past saturation — each client waits for its answer before sending the
next request, so offered load self-limits. Real traffic is *open-loop*:
arrivals do not care how backed up the server is, and past the
saturation point a FIFO queue grows without bound, every queued request
eventually times out client-side, and the engine spends 100% of its
time computing answers nobody is still waiting for — queueing collapse.
This module is the robustness layer that keeps the engine's work *good*
under overload (ROADMAP item 4):

* **deadlines** — every request carries an absolute completion deadline
  (``time.monotonic`` based; assigned from the batcher's
  ``deadline_ms`` default or per-request);
* **earliest-deadline-first dispatch** — :class:`AdmissionController`
  is a deadline-ordered priority queue, so the collector always works
  on the request that will expire soonest (under load, FIFO order and
  EDF order diverge exactly when it matters);
* **shedding before dead work** — at dispatch time, a request whose
  *predicted* completion (:class:`LatencyEstimator`: the rolling
  ``serve.infer_s`` estimate from a PR 7
  :class:`~tpu_syncbn.obs.timeseries.WindowedAggregator` when telemetry
  feeds one, an EWMA of observed engine calls otherwise) already misses
  its deadline is failed immediately (:class:`DeadlineExceededError`)
  instead of being padded into a program — the engine's cycles go to
  requests that can still be answered in time (goodput, not
  throughput);
* **circuit breaking** — :class:`CircuitBreaker`: N *consecutive*
  engine failures open the circuit (submits fast-fail with a
  retry-after, :class:`CircuitOpenError`), the PR 1 deterministic-
  jitter backoff (:func:`tpu_syncbn.runtime.resilience.backoff_delays`)
  schedules half-open probes, and one successful probe batch closes it
  again. Circuit state feeds the batcher's ``/readyz`` hook and the
  ``serve.circuit_state`` gauge (0 closed / 1 half-open / 2 open).

Telemetry (docs/OBSERVABILITY.md): ``serve.shed`` counter (requests
failed by the shed/deadline path), ``serve.deadline_miss_total``
counter (sheds + answers that landed past their deadline), and the
``serve.circuit_state`` gauge. The degradation paths are proven by
injection — ``testing.faults.slow_engine`` / ``crash_engine_at_batch``
/ ``poison_request`` drive them in tests/test_serve_chaos.py, the same
way PR 1 proved training recovery.
"""

from __future__ import annotations

import heapq
import queue
import re
import threading
import time
from typing import Callable

from tpu_syncbn.obs import telemetry

__all__ = [
    "RejectedError",
    "DeadlineExceededError",
    "CircuitOpenError",
    "LatencyEstimator",
    "AdmissionController",
    "CircuitBreaker",
]


class RejectedError(RuntimeError):
    """The batcher refused a request: queue full (backpressure), the
    batcher is draining/closed, or an overload policy shed it. Clients
    should retry elsewhere. ``retry_after_s`` (when not ``None``) is
    the server's backoff hint."""

    def __init__(self, message: str, *, retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DeadlineExceededError(RejectedError):
    """The request's deadline passed (or its predicted completion
    already misses it) — shed instead of computed."""


class CircuitOpenError(RejectedError):
    """The engine circuit is open after consecutive failures — the
    request is fast-failed without queueing. ``retry_after_s`` is the
    remaining backoff before the next half-open probe window."""


# ---------------------------------------------------------------------------
# rolling engine-latency estimate


class LatencyEstimator:
    """Predicted engine-call duration for shed decisions.

    Two sources, in preference order:

    1. the rolling windowed quantile of ``metric`` (default
       ``serve.infer_s``) from a PR 7
       :class:`~tpu_syncbn.obs.timeseries.WindowedAggregator` — the
       live estimate a monitored process already maintains (requires
       the telemetry gate on, since the aggregator samples the
       registry);
    2. an EWMA of durations fed directly via :meth:`observe` (the
       batcher reports every engine call) — always available, telemetry
       gate or not.

    With *no* evidence yet, :meth:`predict` returns ``None`` and the
    admission controller sheds nothing: an overload policy must act on
    measurements, never on a cold guess."""

    def __init__(
        self,
        aggregator=None,
        *,
        metric: str = "serve.infer_s",
        quantile: float = 0.9,
        window_s: float = 30.0,
        alpha: float = 0.3,
    ):
        if not 0.0 < quantile < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {quantile}")
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self._agg = aggregator
        self.metric = metric
        self.quantile = quantile
        self.window_s = float(window_s)
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        self._ewma: float | None = None

    def observe(self, seconds: float) -> None:
        """Feed one observed engine-call duration into the EWMA."""
        s = float(seconds)
        if s < 0:
            return
        with self._lock:
            self._ewma = s if self._ewma is None else (
                self.alpha * s + (1.0 - self.alpha) * self._ewma
            )

    def predict(self) -> float | None:
        """The current per-call duration estimate in seconds, or
        ``None`` before any evidence exists."""
        if self._agg is not None:
            try:
                q = self._agg.quantile(self.metric, self.quantile,
                                       self.window_s)
            except Exception:
                q = None
            if q is not None:
                return float(q)
        with self._lock:
            return self._ewma


# ---------------------------------------------------------------------------
# deadline-ordered admission queue


class AdmissionController:
    """Bounded deadline-priority request queue with dispatch-time
    shedding — the drop-in replacement for the batcher's FIFO
    ``queue.Queue`` (same ``put_nowait`` / ``get`` / ``get_nowait`` /
    ``qsize`` / ``empty`` / ``maxsize`` surface, so the collector loop
    is policy-agnostic).

    Ordering: earliest absolute deadline first; deadline-less requests
    sort after every deadlined one, FIFO among themselves (an admission
    sequence number breaks ties, so the no-deadline configuration is
    *exactly* the old FIFO batcher).

    Shedding happens in :meth:`get`/:meth:`get_nowait`, at the moment a
    request would enter a batch: if its deadline has already passed, or
    ``now + estimator.predict()`` lands past it, the request is handed
    to ``on_shed`` (the batcher fails its future with
    :class:`DeadlineExceededError` and counts ``serve.shed``) and the
    pop moves on — the engine never computes a dead answer. With no
    estimator evidence only already-expired requests are shed.

    ``now`` is injectable for deterministic fault tests."""

    def __init__(
        self,
        *,
        max_queue: int,
        estimator: LatencyEstimator | None = None,
        on_shed: Callable[[object], None] | None = None,
        now: Callable[[], float] = time.monotonic,
    ):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.maxsize = int(max_queue)
        self.estimator = estimator
        self.on_shed = on_shed
        self._now = now
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._heap: list = []  # (deadline or +inf, seq, request)
        self._seq = 0

    # -- queue surface (matches queue.Queue where the batcher uses it) ----

    def qsize(self) -> int:
        with self._lock:
            return len(self._heap)

    def empty(self) -> bool:
        return self.qsize() == 0

    def put_nowait(self, req) -> None:
        """Admit ``req`` (anything with an optional ``deadline``
        attribute). Raises ``queue.Full`` at capacity — backpressure
        stays the batcher's concern."""
        deadline = getattr(req, "deadline", None)
        key = float("inf") if deadline is None else float(deadline)
        with self._not_empty:
            if len(self._heap) >= self.maxsize:
                raise queue.Full
            heapq.heappush(self._heap, (key, self._seq, req))
            self._seq += 1
            self._not_empty.notify()

    def _predict(self) -> float | None:
        """One estimator read per pop pass — computed by the callers
        *outside* the queue lock (a windowed-quantile merge per shed,
        serialized against every submitter, would slow admission down
        exactly at saturation)."""
        return self.estimator.predict() if self.estimator is not None else None

    def _pop_viable_locked(self, shed: list, predicted: float | None):
        """Earliest-deadline request that can still make its deadline;
        doomed ones land in ``shed`` (the caller fires ``on_shed``
        *outside* the lock — shedding resolves client futures, whose
        done-callbacks must never run under the queue lock). ``None``
        when the heap empties."""
        while self._heap:
            _, _, req = heapq.heappop(self._heap)
            deadline = getattr(req, "deadline", None)
            if deadline is None:
                return req
            t = self._now()
            eta = t if predicted is None else t + predicted
            if eta > deadline:
                shed.append(req)
                continue
            return req
        return None

    def _fire_sheds(self, shed: list) -> None:
        if self.on_shed is None:
            return
        for req in shed:
            self.on_shed(req)

    def get_nowait(self):
        shed: list = []
        predicted = self._predict()
        with self._not_empty:
            req = self._pop_viable_locked(shed, predicted)
        self._fire_sheds(shed)
        if req is None:
            raise queue.Empty
        return req

    def get(self, timeout: float | None = None):
        end = None if timeout is None else self._now() + float(timeout)
        while True:
            shed: list = []
            timed_out = False
            predicted = self._predict()
            with self._not_empty:
                req = self._pop_viable_locked(shed, predicted)
                if req is None:
                    remaining = None if end is None else end - self._now()
                    if remaining is not None and remaining <= 0:
                        timed_out = True
                    else:
                        timed_out = not self._not_empty.wait(remaining)
            self._fire_sheds(shed)
            if req is not None:
                return req
            if timed_out:
                raise queue.Empty


# ---------------------------------------------------------------------------
# circuit breaker


class CircuitBreaker:
    """Consecutive-failure circuit breaker with deterministic-jitter
    backoff (PR 1's :func:`~tpu_syncbn.runtime.resilience.backoff_delays`
    — reproducible under the fault harness, de-synchronized across
    hosts by ``key``).

    States: ``closed`` (normal; failures counted), ``open`` (submits
    fast-fail with retry-after until the backoff expires), ``half_open``
    (up to ``probe_limit`` submits — one probe batch's worth — admitted
    until the probe's outcome lands, everything beyond keeps
    fast-failing; success closes, failure re-opens with the next,
    longer backoff). Repeated open→probe→fail cycles walk up the
    backoff schedule; a success resets it.

    State changes publish a circuit-state gauge (0 closed / 1 half-open
    / 2 open): ``serve.circuit_state`` for the default/``serve`` key,
    ``serve.circuit_state.<key>`` otherwise — keyed like the
    ``/healthz`` heartbeats, so two batchers in one process (each with
    its own breaker key) can never mask each other's state. Thread-safe;
    ``now`` injectable for deterministic tests."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"
    _CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(
        self,
        *,
        failure_threshold: int = 5,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 30.0,
        backoff_steps: int = 8,
        probe_limit: int = 8,
        key: str = "",
        now: Callable[[], float] = time.monotonic,
    ):
        from tpu_syncbn.runtime.resilience import backoff_delays

        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        self.failure_threshold = int(failure_threshold)
        # backoff_delays(n) yields n-1 sleeps; +1 so backoff_steps is
        # the number of distinct open->probe delays before saturating
        self._delays = backoff_delays(
            int(backoff_steps) + 1, base_s=backoff_base_s,
            max_s=backoff_max_s, key=key or "serve-circuit",
        )
        if not self._delays:
            raise ValueError(f"backoff_steps must be >= 1, got {backoff_steps}")
        if probe_limit < 1:
            raise ValueError(f"probe_limit must be >= 1, got {probe_limit}")
        self.probe_limit = int(probe_limit)
        token = re.sub(r"[^a-z0-9_]", "_", key.lower())
        if token in ("", "serve"):
            # the default breaker keeps the plain process-wide gauge
            self._labels = None
            self.gauge_name = "serve.circuit_state"
        else:
            # non-default breakers publish the labeled family; the old
            # flat dotted-suffix name mirrors behind a DeprecationWarning
            self._labels = {"family": token}
            self.gauge_name = telemetry.labeled_name(
                "serve.circuit_state", self._labels
            )
            self._legacy_gauge_name = f"serve.circuit_state.{token}"
        self._now = now
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive = 0
        self._open_streak = 0  # opens since the last success
        self._opened_at: float | None = None
        self._retry_after: float = 0.0
        self._probes_admitted = 0  # submits let through while half-open
        self.open_count = 0  # lifetime opens (stats)
        self._publish()

    def _publish(self) -> None:
        code = self._CODES[self._state]
        if self._labels is None:
            telemetry.set_gauge(self.gauge_name, code)
        else:
            telemetry.set_gauge("serve.circuit_state", code,
                                labels=self._labels)
            telemetry.warn_deprecated_name(
                self._legacy_gauge_name, self.gauge_name
            )
            telemetry.set_gauge(self._legacy_gauge_name, code)
        # breaker transitions land in the flight recorder's serve ring
        # (no-op without a recorder; record_serve only takes the
        # recorder's own ring lock — no cross-lock cycle with ours)
        from tpu_syncbn.obs import flightrec

        flightrec.record_serve("circuit_state", state=self._state,
                               breaker=self.gauge_name)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    @property
    def state_code(self) -> int:
        return self._CODES[self.state]

    def _maybe_half_open_locked(self) -> None:
        if self._state == self.OPEN and \
                self._now() - self._opened_at >= self._retry_after:
            self._state = self.HALF_OPEN
            self._probes_admitted = 0
            self._publish()

    def retry_after_s(self) -> float:
        """Seconds until the next half-open probe window (0 when the
        circuit is not open)."""
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(0.0,
                       self._retry_after - (self._now() - self._opened_at))

    def allow(self) -> tuple[bool, float]:
        """Admission verdict: ``(admit, retry_after_s)``. Open circuit
        with backoff remaining → ``(False, remaining)``; an expired
        backoff transitions to half-open and admits up to
        ``probe_limit`` submits (one probe batch's worth) until the
        probe's outcome lands — everything beyond the quota keeps
        fast-failing rather than queueing behind a still-suspect
        engine."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == self.OPEN:
                remaining = max(
                    0.0,
                    self._retry_after - (self._now() - self._opened_at),
                )
                return False, remaining
            if self._state == self.HALF_OPEN:
                if self._probes_admitted >= self.probe_limit:
                    # quota spent, probe outcome pending: the hint is
                    # the backoff a failed probe would impose
                    idx = min(self._open_streak, len(self._delays) - 1)
                    return False, self._delays[idx]
                self._probes_admitted += 1
            return True, 0.0

    def record_success(self) -> None:
        """One engine call succeeded: half-open probe success closes
        the circuit; any success resets the failure count and the
        backoff schedule."""
        with self._lock:
            changed = self._state != self.CLOSED
            self._state = self.CLOSED
            self._consecutive = 0
            self._open_streak = 0
            self._opened_at = None
            self._probes_admitted = 0
            if changed:
                self._publish()

    def record_failure(self) -> bool:
        """One engine call failed. Returns True when this failure
        opened (or re-opened) the circuit."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == self.HALF_OPEN:
                # failed probe: straight back to open, longer backoff
                opened = True
            else:
                self._consecutive += 1
                opened = (self._state == self.CLOSED
                          and self._consecutive >= self.failure_threshold)
            if opened:
                self._state = self.OPEN
                self._opened_at = self._now()
                idx = min(self._open_streak, len(self._delays) - 1)
                self._retry_after = self._delays[idx]
                self._open_streak += 1
                self.open_count += 1
                self._consecutive = 0
                self._publish()
            retry_after = self._retry_after
        if opened:
            # incident capture OUTSIDE the breaker lock: the dump reads
            # readiness hooks, which read this breaker's stats() — a
            # trigger under self._lock would deadlock on itself
            from tpu_syncbn.obs import flightrec

            flightrec.trigger("circuit_open", {
                "breaker": self.gauge_name,
                "open_count": self.open_count,
                "retry_after_s": round(retry_after, 4),
            })
        return opened

    def stats(self) -> dict:
        """JSON-ready breaker state for readiness detail blocks."""
        with self._lock:
            self._maybe_half_open_locked()
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "open_count": self.open_count,
                "retry_after_s": round(max(
                    0.0,
                    (self._retry_after - (self._now() - self._opened_at))
                    if self._state == self.OPEN else 0.0,
                ), 4),
            }
