"""Dynamic request batching: queueing, admission, backpressure, drain.

The engine (:mod:`tpu_syncbn.serve.engine`) executes *batches*; real
traffic arrives as small independent requests. :class:`DynamicBatcher`
sits between them — the reference recipe has no serving story at all, so
this is the standard dynamic-batching design (bounded queue + a single
collector thread) rebuilt on this codebase's seams:

* **admission policy** — a batch dispatches when it reaches
  ``max_batch`` items OR its oldest request has waited ``max_wait_ms``,
  whichever comes first: full batches under load (throughput), bounded
  queueing delay when idle (latency);
* **backpressure** — the request queue is bounded (``max_queue``); a
  full queue *rejects* the submit (:class:`RejectedError`) instead of
  growing latency without bound — load shedding at the edge, where the
  client can retry against another replica;
* **deadlines + shedding** — with ``deadline_ms`` set (or per-request
  via ``submit(..., deadline_ms=)``) the queue becomes
  earliest-deadline-first (:class:`~tpu_syncbn.serve.admission.
  AdmissionController`), and requests whose predicted completion
  already misses their deadline are shed
  (:class:`~tpu_syncbn.serve.admission.DeadlineExceededError`,
  ``serve.shed`` / ``serve.deadline_miss_total``) before the engine
  does dead work — bounded p99 past saturation instead of queueing
  collapse (ROADMAP item 4);
* **circuit breaking** — consecutive engine failures open a
  :class:`~tpu_syncbn.serve.admission.CircuitBreaker`: submits
  fast-fail with a retry-after hint, PR 1's deterministic-jitter
  backoff schedules half-open probes, circuit state feeds ``/readyz``
  and the ``serve.circuit_state`` gauge;
* **graceful drain** — wired to PR 1's preemption contract: give the
  batcher a :class:`~tpu_syncbn.runtime.resilience.PreemptionGuard`
  (anything with a truthy ``preempted`` property works) and the first
  SIGTERM flips it into drain mode — new submits are rejected, every
  already-admitted request is answered, then the worker exits. The same
  drain runs on ``close(drain=True)``.

Coalesced requests are concatenated along the batch axis, padded to a
bucket by the engine, and each caller's slice is handed back through its
``concurrent.futures.Future``. The engine is only ever called from the
single collector thread, so jax never sees concurrent dispatch.

Observability (docs/OBSERVABILITY.md): ``serve.latency_s``
enqueue→response histogram, ``serve.queue_depth`` gauge,
``serve.batch_fill_ratio`` histogram, a ``serve.batch`` trace span per
executed batch, and a ``CounterGroup`` (prefix ``serve``) whose counts —
``requests`` / ``rejected`` / ``batches`` / ``items`` / ``slots`` /
``errors`` — always accumulate locally and mirror into the process
registry when telemetry is enabled.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from tpu_syncbn.obs import flightrec
from tpu_syncbn.obs import server as obs_server
from tpu_syncbn.obs import stepstats as obs_stepstats
from tpu_syncbn.obs import telemetry
from tpu_syncbn.obs.tracing import get as active_tracer
from tpu_syncbn.runtime import distributed as dist
from tpu_syncbn.serve.admission import (  # noqa: F401  (re-exported API)
    AdmissionController,
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    LatencyEstimator,
    RejectedError,
)

__all__ = ["DynamicBatcher", "RejectedError", "DeadlineExceededError",
           "CircuitOpenError"]

#: Fill-ratio histogram boundaries (a ratio in (0, 1], not a duration).
FILL_BUCKETS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)


#: Process-unique request ids — the Perfetto flow ids linking each
#: request's enqueue span to the batch span that answered it.
_request_ids = itertools.count(1)


class _Request:
    __slots__ = ("payload", "n", "future", "t0", "deadline", "rid")

    def __init__(self, payload, n: int, deadline: float | None = None):
        self.payload = payload
        self.n = n
        self.future: Future = Future()
        self.t0 = time.perf_counter()
        #: absolute completion deadline on time.monotonic, or None
        self.deadline = deadline
        self.rid = next(_request_ids)


class DynamicBatcher:
    """Coalesce single requests into engine batches.

    ``engine`` needs ``bucket_for(n)``, ``max_bucket``, and
    ``predict(batch) -> host outputs`` (duck-typed; tests drive the
    queueing logic with a stub). ``max_batch`` defaults to the engine's
    largest bucket and may not exceed it — an admitted batch must always
    fit one program. ``guard`` is the preemption hook (see module
    docstring).

    ``submit(item)`` takes a host batch pytree with a leading axis of
    ``n >= 1`` (a single example is ``x[i:i+1]``) and returns a
    ``Future`` resolving to that request's output slice.

    Overload policy knobs (docs/RESILIENCE.md "Serving failure modes"):

    * ``deadline_ms`` — default completion deadline per request
      (``submit(..., deadline_ms=)`` overrides per call; ``None``
      disables deadlines entirely, which is exactly the historical FIFO
      batcher). Deadlined requests dispatch earliest-deadline-first and
      are shed once their predicted completion misses the deadline.
    * ``estimator`` — the :class:`~tpu_syncbn.serve.admission.
      LatencyEstimator` feeding shed decisions; by default one is built
      that EWMAs this batcher's own observed engine calls (hand it one
      wrapping a :class:`~tpu_syncbn.obs.timeseries.WindowedAggregator`
      to use the rolling windowed ``serve.infer_s`` quantile instead).
    * ``breaker`` — the engine :class:`~tpu_syncbn.serve.admission.
      CircuitBreaker`; default-constructed (5 consecutive failures
      open). Pass a configured instance, or ``False`` to disable.
    * ``tenant`` — optional tenant name: traffic series (``requests`` /
      ``rejected`` / ``shed`` / ``deadline_miss_total`` counters, the
      ``serve.latency_s`` histogram, the ``serve.queue_depth`` gauge)
      additionally publish ``{tenant="..."}``-labeled twins, and serve-
      ring entries carry the tenant — the per-tenant SLO substrate
      (docs/OBSERVABILITY.md "Labels & cardinality").
    """

    def __init__(
        self,
        engine,
        *,
        max_batch: int | None = None,
        max_wait_ms: float = 5.0,
        max_queue: int = 64,
        guard: Any = None,
        ready_depth: int | None = None,
        health_name: str = "serve",
        deadline_ms: float | None = None,
        estimator: LatencyEstimator | None = None,
        breaker: CircuitBreaker | bool | None = None,
        tenant: str | None = None,
    ):
        if max_batch is None:
            max_batch = int(engine.max_bucket)
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_batch > engine.max_bucket:
            raise ValueError(
                f"max_batch={max_batch} exceeds the engine's largest "
                f"bucket {engine.max_bucket} — a full batch must fit one "
                "compiled program"
            )
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        self._engine = engine
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self._guard = guard
        #: optional ``tenant`` label: when set, this batcher publishes
        #: labeled twins of its serve.* traffic series alongside the
        #: unlabeled process-wide ones, so two tenants sharing one mesh
        #: get separately addressable rates/quantiles/burn rates
        self.tenant = tenant
        self._tenant_labels = {"tenant": tenant} if tenant else None
        #: tenant attribution for flight-recorder serve-ring entries
        self._detail = {"tenant": tenant} if tenant else {}
        self.default_deadline_ms = deadline_ms
        self.estimator = (estimator if estimator is not None
                          else LatencyEstimator())
        if breaker is None:
            breaker = CircuitBreaker(key=health_name)
        self._breaker: CircuitBreaker | None = breaker or None
        self._q = AdmissionController(
            max_queue=max_queue, estimator=self.estimator,
            on_shed=self._shed,
        )
        self._closing = False
        self._drain_on_close = True
        self._stopped = threading.Event()
        #: always-on local counts; mirrored into the registry as
        #: ``serve.*`` when telemetry is enabled (obs.CounterGroup)
        self.counters = telemetry.CounterGroup(prefix="serve")
        self._log = dist.get_logger("tpu_syncbn.serve")
        # live monitoring (docs/OBSERVABILITY.md "Live monitoring"):
        # with TPU_SYNCBN_METRICS_PORT set this process answers
        # /metrics + /healthz (collector heartbeat) + /readyz (the
        # ``health_name`` hook below — give each batcher in a
        # multi-model process a distinct name: registration replaces,
        # and close() clears, whatever holds that name).
        # ready_depth defaults to 90% of
        # the queue bound: readiness must flip BEFORE the queue-full
        # rejection path starts shedding, so a balancer routes away
        # while there is still headroom.
        if ready_depth is None:
            ready_depth = max(1, (9 * max_queue) // 10)
        if not 1 <= ready_depth <= max_queue:
            raise ValueError(
                f"ready_depth must be in [1, max_queue={max_queue}], "
                f"got {ready_depth}"
            )
        self.ready_depth = int(ready_depth)
        self._health_name = str(health_name)
        obs_server.start_from_env()
        # flight recorder (docs/OBSERVABILITY.md "Incidents"): serve
        # decisions (sheds, rejections, deadline misses, breaker
        # transitions) ring-buffer into it; a circuit open dumps a
        # bundle. TPU_SYNCBN_FLIGHTREC is the whole knob.
        flightrec.install_from_env()
        # memory watermarks (docs/OBSERVABILITY.md "Memory & compile"):
        # TPU_SYNCBN_MEMWATCH arms the background sampler — bucket churn
        # evicting programs and a tenant walking toward OOM both become
        # visible (and incident-triggering) without code changes
        from tpu_syncbn.obs import memwatch

        memwatch.install_from_env()
        obs_server.register_readiness(self._health_name, self.readiness)
        self._thread = threading.Thread(
            target=self._run, name="dynamic-batcher", daemon=True
        )
        self._thread.start()

    # -- accessors ---------------------------------------------------------
    # public views for the publication path (serve.publish.SwapController
    # pulls the breaker as its post-swap health signal and the guard as
    # its drain signal, and swaps versions on the engine underneath a
    # running batcher)

    @property
    def engine(self):
        """The engine this batcher feeds."""
        return self._engine

    @property
    def breaker(self) -> "CircuitBreaker | None":
        """The admission circuit breaker (None when disabled)."""
        return self._breaker

    @property
    def guard(self):
        """The preemption guard wired at construction (or None)."""
        return self._guard

    # -- admission ---------------------------------------------------------

    @property
    def draining(self) -> bool:
        """True once a preemption signal or close() stopped admission."""
        return self._closing or (
            self._guard is not None and bool(self._guard.preempted)
        )

    @property
    def drained(self) -> bool:
        """True once the worker has answered everything and exited."""
        return self._stopped.is_set() and self._q.empty()

    @property
    def fill_ratio(self) -> float | None:
        """Aggregate batch-fill ratio so far: admitted items over padded
        program slots (1.0 = every program ran completely full)."""
        slots = self.counters.count("slots")
        if not slots:
            return None
        return self.counters.count("items") / slots

    def readiness(self) -> tuple[bool, dict]:
        """The batcher's ``/readyz`` contribution (registered as the
        ``health_name`` hook, default ``serve``): ready while admission
        is open (not draining/closed), the queue depth is below
        ``ready_depth`` — overload flips the probe before backpressure
        has to reject — AND the engine circuit is not open (a broken
        engine flips the probe before clients pay fast-rejections;
        half-open reads ready again, since probe traffic has to come
        from somewhere). The detail block carries the live queue +
        circuit state plus the engine's health summary when it offers
        one."""
        depth = self._q.qsize()
        draining = self.draining
        circuit_open = (self._breaker is not None
                        and self._breaker.state == CircuitBreaker.OPEN)
        ok = not draining and not self._stopped.is_set() \
            and depth < self.ready_depth and not circuit_open
        detail = {
            "queue_depth": depth,
            "ready_depth": self.ready_depth,
            "max_queue": self._q.maxsize,
            "draining": draining,
        }
        if self._breaker is not None:
            detail["circuit"] = self._breaker.stats()
        engine_health = getattr(self._engine, "health", None)
        if callable(engine_health):
            try:
                detail["engine"] = engine_health()
            except Exception as e:  # detail, never the verdict
                detail["engine"] = {"error": f"{type(e).__name__}: {e}"}
        return ok, detail

    def _shed(self, req: _Request) -> None:
        """Fail one deadline-doomed request (the admission controller's
        ``on_shed``): the engine never sees it — shedding dead work is
        the point. Counts ``serve.shed`` and ``serve.deadline_miss_total``."""
        if req.future.set_running_or_notify_cancel():
            req.future.set_exception(DeadlineExceededError(
                "shed: predicted completion misses the request deadline"
            ))
        self.counters.bump("shed", labels=self._tenant_labels)
        self.counters.bump("deadline_miss_total",
                           labels=self._tenant_labels)
        flightrec.record_serve("shed", rid=req.rid, n=req.n,
                               **self._detail)

    def submit(self, item, *, deadline_ms: float | None = None) -> Future:
        """Enqueue one request; returns its ``Future``. Raises
        :class:`RejectedError` on backpressure (queue full), once the
        batcher is draining/closed, or — fast, without queueing — while
        the engine circuit is open (:class:`CircuitOpenError`, with a
        ``retry_after_s`` hint). ``deadline_ms`` overrides the
        batcher's default completion deadline for this request."""
        n = _leading(item)
        if n > self.max_batch:
            raise RejectedError(
                f"request of {n} items exceeds max_batch={self.max_batch}; "
                "split it or call the engine directly"
            )
        if self.draining or self._stopped.is_set():
            self.counters.bump("rejected", labels=self._tenant_labels)
            flightrec.record_serve("rejected", reason="draining", n=n,
                                   **self._detail)
            raise RejectedError("batcher is draining — not admitting")
        if self._breaker is not None:
            admit, retry_after = self._breaker.allow()
            if not admit:
                self.counters.bump("rejected",
                                   labels=self._tenant_labels)
                flightrec.record_serve("rejected", reason="circuit_open",
                                       n=n, **self._detail)
                raise CircuitOpenError(
                    "engine circuit open after consecutive failures — "
                    f"retry in {retry_after:.2f}s",
                    retry_after_s=retry_after,
                )
        dl_ms = (deadline_ms if deadline_ms is not None
                 else self.default_deadline_ms)
        if dl_ms is not None and dl_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {dl_ms}")
        deadline = (None if dl_ms is None
                    else time.monotonic() + float(dl_ms) / 1e3)
        req = _Request(item, n, deadline)
        tracer = active_tracer()
        if tracer is not None:
            # flow start: Perfetto draws an arrow from this enqueue
            # span to the serve.batch span that answers the request
            # (flow id = request id), making batching latency visually
            # attributable in any trace of this process
            with tracer.span("serve.enqueue", rid=req.rid, n=n):
                tracer.flow_start("serve.request", req.rid)
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self.counters.bump("rejected", labels=self._tenant_labels)
            flightrec.record_serve("rejected", reason="queue_full", n=n,
                                   **self._detail)
            raise RejectedError(
                f"request queue full ({self._q.maxsize}) — shed load"
            ) from None
        if self._stopped.is_set():
            # the worker can drain-and-exit between the admission check
            # above and the put landing — nothing may rot in a dead
            # queue, so fail whatever is still in it (possibly our own
            # request; a result already set by the worker wins)
            self._reject_dead_queue()
            if req.future.done() and req.future.exception() is not None:
                self.counters.bump("rejected", labels=self._tenant_labels)
                raise RejectedError("batcher is draining — not admitting")
        self.counters.bump("requests", labels=self._tenant_labels)
        telemetry.set_gauge("serve.queue_depth", self._q.qsize())
        if self._tenant_labels is not None:
            telemetry.set_gauge("serve.queue_depth", self._q.qsize(),
                                labels=self._tenant_labels)
        return req.future

    def _reject_dead_queue(self) -> None:
        """The worker has exited; answer anything still queued with the
        drain rejection so no Future blocks forever."""
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(
                    RejectedError("batcher is draining — not admitting")
                )

    # -- collector ---------------------------------------------------------

    def _run(self) -> None:
        carry: _Request | None = None
        try:
            while True:
                # collector liveness: a wedged engine call stops this
                # beat, and /healthz goes stale — the "stuck mid-batch"
                # signal a balancer can act on. Keyed by health_name so
                # two batchers in one process (give the second a
                # distinct name) cannot mask each other's stall.
                obs_server.HEARTBEATS.beat(self._health_name)
                if carry is not None:
                    first, carry = carry, None
                else:
                    try:
                        first = self._q.get(timeout=0.01)
                    except queue.Empty:
                        if self.draining:
                            break
                        continue
                if self._closing and not self._drain_on_close:
                    if first.future.set_running_or_notify_cancel():
                        first.future.set_exception(
                            RejectedError("batcher closed without drain")
                        )
                    continue
                if self._breaker is not None:
                    admit, retry_after = self._breaker.allow()
                    if not admit:
                        # open circuit: already-queued work fast-fails
                        # too — dispatching it into a known-broken
                        # engine would only delay the client's retry
                        self.counters.bump("rejected",
                                           labels=self._tenant_labels)
                        if first.future.set_running_or_notify_cancel():
                            first.future.set_exception(CircuitOpenError(
                                "engine circuit open — retry in "
                                f"{retry_after:.2f}s",
                                retry_after_s=retry_after,
                            ))
                        continue
                reqs, n = [first], first.n
                deadline = first.t0 + self.max_wait_s
                while n < self.max_batch:
                    wait = (0.0 if self.draining
                            else deadline - time.perf_counter())
                    try:
                        r = (self._q.get(timeout=wait) if wait > 0
                             else self._q.get_nowait())
                    except queue.Empty:
                        break
                    if n + r.n > self.max_batch:
                        carry = r  # opens the next batch
                        break
                    reqs.append(r)
                    n += r.n
                self._execute(reqs)
        finally:
            self._stopped.set()

    def _execute(self, reqs: list[_Request]) -> None:
        import jax

        # claim every request (RUNNING) before touching payloads: a
        # client that cancelled while queued is silently dropped, and a
        # claimed future can no longer be cancelled out from under the
        # set_result below
        live = [r for r in reqs if r.future.set_running_or_notify_cancel()]
        if not live:
            return
        n = sum(r.n for r in live)
        try:
            bucket = self._engine.bucket_for(n)
            payload = jax.tree_util.tree_map(
                lambda *ls: np.concatenate(
                    [np.asarray(l) for l in ls], axis=0
                ),
                *[r.payload for r in live],
            )
        except Exception as e:
            # coalescing failures (e.g. requests whose trailing shapes
            # disagree reach np.concatenate) are *request* errors: fail
            # the batch, never the collector thread — and never the
            # circuit breaker, which guards the ENGINE
            self.counters.bump("errors")
            self._log.exception("serve coalesce failed (%d requests)",
                                len(live))
            for r in live:
                r.future.set_exception(e)
            return
        t_call = time.perf_counter()
        try:
            with obs_stepstats.timed_span(
                "serve.batch", "serve.batch_s", n=n, bucket=bucket,
                requests=len(live),
            ):
                tracer = active_tracer()
                if tracer is not None:
                    # flow ends INSIDE the batch span so the arrows
                    # terminate on it (bp="e" binds to the enclosing
                    # slice)
                    for r in live:
                        tracer.flow_end("serve.request", r.rid)
                out = self._engine.predict(payload)
        except Exception as e:  # answer everyone; keep serving
            self.counters.bump("errors")
            self._log.exception("serve batch failed (%d requests)",
                                len(live))
            if self._breaker is not None \
                    and self._breaker.record_failure():
                self._log.error(
                    "engine circuit OPENED after %d consecutive "
                    "failures — fast-rejecting with retry-after %.2fs",
                    self._breaker.failure_threshold,
                    self._breaker.retry_after_s(),
                )
            for r in live:
                r.future.set_exception(e)
            return
        self.estimator.observe(time.perf_counter() - t_call)
        if self._breaker is not None:
            self._breaker.record_success()
        reqs = live
        now = time.perf_counter()
        mono = time.monotonic()
        off = 0
        for r in reqs:
            lo = off
            off += r.n
            telemetry.observe("serve.latency_s", now - r.t0)
            if self._tenant_labels is not None:
                telemetry.observe("serve.latency_s", now - r.t0,
                                  labels=self._tenant_labels)
            if r.deadline is not None and mono > r.deadline:
                # answered, but late: the client may already have given
                # up — count it so the miss rate covers late answers,
                # not just sheds
                self.counters.bump("deadline_miss_total",
                                   labels=self._tenant_labels)
                flightrec.record_serve(
                    "deadline_miss", rid=r.rid,
                    late_s=round(mono - r.deadline, 4), **self._detail,
                )
            r.future.set_result(jax.tree_util.tree_map(
                lambda a: a[lo:lo + r.n], out
            ))
        self.counters.bump("batches")
        self.counters.bump("items", n)
        self.counters.bump("slots", bucket)
        telemetry.observe("serve.batch_fill_ratio", n / bucket, FILL_BUCKETS)
        telemetry.set_gauge("serve.queue_depth", self._q.qsize())

    # -- shutdown ----------------------------------------------------------

    def close(self, *, drain: bool = True, timeout: float | None = None) -> None:
        """Stop the batcher. ``drain=True`` (default) answers every
        already-admitted request first — the preemption-exit path;
        ``drain=False`` fails pending requests with
        :class:`RejectedError`. Idempotent.

        With a ``timeout``, a collector thread that fails to join —
        an engine call wedged inside :meth:`_execute` — is **surfaced**
        (logged and raised as :class:`TimeoutError`), never reported as
        a clean shutdown; the heartbeat and readiness hook are left
        registered so ``/healthz`` keeps naming the stall."""
        self._drain_on_close = self._drain_on_close and drain
        self._closing = True
        self._thread.join(timeout)
        if self._thread.is_alive():
            self.counters.bump("close_timeouts")
            self._log.error(
                "batcher close(timeout=%s) did NOT stop the collector — "
                "the engine call is wedged; /healthz heartbeat %r stays "
                "registered to flag the stall", timeout, self._health_name,
            )
            raise TimeoutError(
                f"DynamicBatcher collector failed to join within "
                f"{timeout}s — engine call wedged; not a clean shutdown"
            )
        # a cleanly-closed batcher must not leave a stale heartbeat
        # (false liveness failure) or a permanently not-ready hook
        obs_server.HEARTBEATS.clear(self._health_name)
        obs_server.unregister_readiness(self._health_name)

    def __enter__(self) -> "DynamicBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _leading(item) -> int:
    from tpu_syncbn.serve.engine import _leading_dim

    n = _leading_dim(item)  # validates cross-leaf agreement up front
    if n < 1:
        raise ValueError(
            "requests need a leading batch axis of >= 1 (a single example "
            "is x[i:i+1])"
        )
    return n
