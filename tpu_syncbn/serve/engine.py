"""Bucketed AOT inference engine: the serving-side execution core.

Training ends with parameters in a *training* layout — replicated pytrees
(or, under ``DataParallel(zero=True)``, dtype-grouped flat vectors sharded
1/world across the data axis) with BN statistics accumulated into
``BatchStat`` buffers. Serving needs the opposite arrangement: params
gathered out of their shards and re-replicated once
(:func:`tpu_syncbn.parallel.zero.unshard_params` — the layout-change
problem of "Memory-efficient array redistribution through portable
collective communication", arxiv 2112.01075, at whole-model granularity),
the model pinned in eval mode so BatchNorm normalizes with running stats
(``nn/normalization.py`` eval fallback: zero collectives — which is what
makes eval embarrassingly parallel over the ``data`` axis), and a small,
*fixed* set of compiled programs so request traffic never waits on XLA.

:class:`InferenceEngine` owns that arrangement:

* **shape buckets** — incoming batches are padded up to the nearest
  configured bucket size, so the compile cache sees a handful of shapes
  no matter what sizes clients send; bucket sizes are normalized up to
  multiples of the mesh world so every program shards evenly over
  ``DATA_AXIS``;
* **AOT compilation** — each bucket's eval program is lowered and
  compiled ahead of its first request (``jit.lower(...).compile()``);
  the compiled executable is what requests run, so the request path
  never traces;
* **size-aware LRU program retention** — compiled programs are cached
  through :func:`tpu_syncbn.parallel.scan_driver.cached_program` into a
  :class:`~tpu_syncbn.parallel.scan_driver.ProgramCache`: at most
  :data:`~tpu_syncbn.parallel.scan_driver.MAX_CACHED_PROGRAMS` live
  (optionally also a byte budget via ``program_cache_bytes``, sized
  from XLA's per-program ``memory_analysis``), least-recently-used
  evicted first — so a client sending pathological shape traffic cannot
  grow device memory without bound, while the hot bucket set stays
  compiled;
* **sharded eval** — the padded global batch is split over the data
  axis (``P('data')`` in / ``P('data')`` out), each replica runs the
  collective-free eval forward on its shard, and results are gathered
  back to host numpy.

The request-coalescing half (queueing, admission policy, backpressure,
drain) lives in :mod:`tpu_syncbn.serve.batcher`.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Sequence

import numpy as np

from tpu_syncbn.runtime.distributed import DATA_AXIS

__all__ = ["InferenceEngine", "VersionSkewError"]


class VersionSkewError(ValueError):
    """A proposed weight swap's parameter tree does not match the
    serving structure (treedef, leaf shapes, or dtypes) — the publisher
    is running a different model schema than this engine. Rejected
    *before* any serving state is touched: the compiled bucket programs
    were lowered against the current structure, so a skewed swap could
    never reuse them."""


def _leading_dim(batch) -> int:
    """The (validated) shared leading-axis length of a batch pytree."""
    import jax

    leaves = jax.tree_util.tree_leaves(batch)
    if not leaves:
        raise ValueError("batch pytree has no array leaves")
    ns = {int(np.shape(l)[0]) if np.ndim(l) else None for l in leaves}
    if len(ns) != 1 or None in ns:
        raise ValueError(
            f"batch leaves disagree on the leading (batch) axis: {ns}"
        )
    return ns.pop()


class InferenceEngine:
    """Throughput-oriented eval executor for a converted model.

    ``model`` is a trained nnx module (typically
    ``convert_sync_batchnorm``-converted, then trained through
    ``DataParallel``); the engine flips it to eval mode — nnx's
    ``model.eval()`` propagates ``use_running_average=True`` through
    every converted submodule (regression-pinned in
    tests/test_nn_modules.py) — splits it once, and re-replicates the
    state onto ``mesh``. Build one from a live trainer with
    :meth:`from_trainer`, which routes ZeRO flat shards through
    ``parallel.zero.unshard_params`` before replicating.

    ``apply_fn(model, batch) -> outputs`` is the eval forward (default:
    ``model(batch)``); every output leaf must carry the batch axis
    leading — outputs are sharded ``P(data)`` and gathered to host.

    ``buckets`` are *global* batch sizes; each is rounded up to a
    multiple of the mesh world (the data axis must divide the padded
    batch). :meth:`predict` pads a request batch up to the smallest
    bucket that fits, runs that bucket's AOT-compiled program, and
    slices the padding back off; batches larger than the biggest bucket
    are chunked through it.

    Telemetry (``TPU_SYNCBN_TELEMETRY`` / bench force-enable):
    ``serve.infer_s`` per-program-call histogram, ``serve.compiles``
    counter + ``serve.compile_s`` histogram, and a ``serve.infer`` trace
    span per call (docs/OBSERVABILITY.md).
    """

    def __init__(
        self,
        model,
        *,
        mesh=None,
        axis_name: str = DATA_AXIS,
        layout=None,
        apply_fn: Callable[[Any, Any], Any] | None = None,
        buckets: Sequence[int] = (8, 32, 128),
        program_cache_bytes: int | None = None,
        model_label: str | None = None,
    ):
        import jax
        from flax import nnx
        from jax.sharding import PartitionSpec as P

        from tpu_syncbn import compat
        from tpu_syncbn.parallel.layout import SpecLayout
        from tpu_syncbn.parallel.trainer import _pallas_forces_vma_off
        from tpu_syncbn.runtime import distributed as dist

        if layout is None:
            layout = SpecLayout.from_mesh(
                mesh if mesh is not None else dist.data_parallel_mesh(),
                param_shard_axis=None,
            )
        elif mesh is not None and mesh != layout.mesh:
            raise ValueError(
                "InferenceEngine: both mesh= and layout= given and they "
                "disagree — pass the layout alone (it carries its mesh)"
            )
        self.layout = layout
        self.mesh = layout.mesh
        self.axis_name = (
            layout.batch_entry if layout.batch_entry is not None
            else axis_name
        )
        self.world = int(layout.replica_world)
        self._apply_fn = apply_fn if apply_fn is not None else (
            lambda m, b: m(b)
        )
        if not buckets:
            raise ValueError("need at least one bucket size")
        norm = sorted({
            int(b) + (-int(b)) % self.world for b in buckets if int(b) >= 1
        })
        if not norm:
            raise ValueError(f"no usable bucket sizes in {buckets!r}")
        #: normalized global bucket sizes (ascending, multiples of world)
        self.buckets: tuple[int, ...] = tuple(norm)

        # eval mode ONCE, at the seam where training state becomes
        # serving state: BN on running stats, dropout-style flags off.
        # The module itself is NOT retained — only the split graphdef +
        # device-put state, so the host-side param tree can be freed.
        model.eval()
        self.graphdef, params, rest = nnx.split(model, nnx.Param, ...)
        self._replicated = layout.replicated
        self.batch_sharding = layout.batch_sharding
        # restore/reshard once: whatever layout the state arrived in
        # (host pytree from unshard_params, trainer-replicated arrays),
        # serving storage is owned by THIS mesh. Under a param-sharding
        # layout (fsdp-composed trainers) the params are stored as flat
        # 1/shard_world dtype-group shards — the eval program gathers
        # them on the wire, so no device ever holds a replicated copy
        # (the max_replicated_bytes the sharding goldens pin shrinks
        # accordingly). Otherwise params replicate as before.
        self._shard_axis = layout.param_shard_axis
        self._shard_world = int(layout.shard_world)
        if self._shard_axis is not None:
            from tpu_syncbn.parallel.zero import FlatLayout

            self._flat = FlatLayout(params, self._shard_world)
            self._store_sharding = layout.sharding(P(self._shard_axis))
            # full-tree structure template: swap_params validates
            # incoming trees against the model, not the flat store
            self._param_template_specs = self._struct_specs(params)
            params_store = self._own_store(self._flat.flatten(params))
        else:
            self._flat = None
            self._store_sharding = self._replicated
            self._param_template_specs = None
            params_store = self._own_replicated(params)
        # Versioned storage: ONE attribute holds (version, params, rest)
        # so a predict call captures a consistent triple with a single
        # atomic read — in-flight batches finish on the version they
        # started on while a concurrent swap_params() lands the next one
        # (the double-buffer half of serve.publish's zero-downtime swap)
        self._state: tuple[int, Any, Any] = (
            0,
            params_store,
            self._own_replicated(rest),
        )
        self._previous: tuple[int, Any, Any] | None = None
        self._swap_lock = threading.Lock()
        # same interpret-lowering concession as the trainer (see
        # DataParallel.__init__): eval BN on running stats never traces
        # the Pallas train kernels, but track_running_stats=False models
        # eval on the batch-stats path, which can trace them — so the
        # VMA checker follows the trainer's gate
        self._check_vma = compat.HAS_VMA and not _pallas_forces_vma_off(model)

        from tpu_syncbn.parallel import scan_driver

        # size-aware LRU via scan_driver (ROADMAP 4: smarter than
        # FIFO-4); hit/miss/eviction accounted so the bucket-program
        # cache hit rate is measurable
        self._programs = scan_driver.ProgramCache(
            name="serve", max_bytes=program_cache_bytes
        )
        self._programs_compiled = 0
        #: optional ``model`` label: when set, the engine publishes
        #: labeled twins of its serve.* series alongside the unlabeled
        #: process-wide ones (multi-model tenancy attribution)
        self.model_label = model_label
        self._model_labels = (
            {"model": model_label} if model_label else None
        )

    # -- versioned state ---------------------------------------------------

    @property
    def _params(self):
        return self._state[1]

    @property
    def _rest(self):
        return self._state[2]

    @property
    def version(self) -> int:
        """The weight version new requests run on (0 = as-constructed)."""
        return self._state[0]

    @property
    def previous_version(self) -> int | None:
        """The retained rollback target's version, or None."""
        prev = self._previous
        return prev[0] if prev is not None else None

    def _struct_specs(self, tree):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        # metadata only (shape/dtype attributes) — no host transfer per
        # leaf on the swap path, and no touching possibly-donated data
        return treedef, tuple(
            (tuple(np.shape(l)),
             str(getattr(l, "dtype", None) or np.asarray(l).dtype))
            for l in leaves
        )

    def _own_replicated(self, tree):
        """``device_put`` to the replicated serving layout, COPYING any
        leaf the put would merely alias: a no-op ``device_put`` returns
        the caller's own array object, and a trainer that later donates
        that buffer (``train_step``) would delete the serving state out
        from under in-flight requests. The engine owns every buffer it
        serves."""
        import jax

        def one(leaf):
            arr = jax.device_put(leaf, self._replicated)
            return arr.copy() if arr is leaf else arr

        return jax.tree_util.tree_map(one, tree)

    def _own_store(self, vecs):
        """``device_put`` flat param vectors to the sharded serving
        layout (``P(shard_axis)``), with the same copy-on-alias
        ownership rule as :meth:`_own_replicated`."""
        import jax

        def one(leaf):
            arr = jax.device_put(leaf, self._store_sharding)
            return arr.copy() if arr is leaf else arr

        return {dt: one(v) for dt, v in vecs.items()}

    def param_template(self):
        """The serving parameters as a FULL pytree (the model's
        structure) regardless of storage layout — the checkpoint/
        publication template. Replicated engines return the store
        itself; sharded engines gather the flat shards through host
        memory (publication load is a host path anyway)."""
        if self._flat is None:
            return self._params
        return self._flat.unflatten_host(self._params)

    def params_nbytes(self) -> int:
        """Per-device bytes of the replicated serving state (params +
        rest) — what a swap's transient double-buffer adds on top while
        old and new versions coexist (the ``memwatch`` pre-flight bound
        in :mod:`tpu_syncbn.serve.publish`)."""
        import jax

        def total(tree):
            return sum(
                int(getattr(l, "nbytes", np.asarray(l).nbytes))
                for l in jax.tree_util.tree_leaves(tree)
            )

        pb = total(self._params)
        if self._flat is not None:
            # flat store: each device holds a 1/shard_world slice
            pb //= self._shard_world
        return pb + total(self._rest)

    def swap_params(self, params, rest=None, *, version: int) -> int:
        """Atomically replace the serving weights with ``params`` (and
        ``rest`` — BN running stats etc. — when given), as weight
        version ``version``. Returns the version swapped out.

        The new state must match the current structure exactly (treedef
        + per-leaf shape/dtype) — the AOT bucket programs were lowered
        against that structure and take the state as *runtime
        arguments*, so a matching swap reuses every compiled program
        with zero recompiles, while a mismatch raises
        :class:`VersionSkewError` before anything is touched. The
        outgoing version is retained as the rollback target
        (:meth:`rollback`); in-flight batches that already captured the
        old triple finish on it untouched (the ``_state`` single-read
        contract)."""
        import jax

        with self._swap_lock:
            old = self._state
            # sharded store: validate against the model's FULL tree
            # template (the flat shards are an internal layout), then
            # flatten and re-shard; replicated store compares directly
            expect = (
                self._param_template_specs if self._flat is not None
                else self._struct_specs(old[1])
            )
            if self._struct_specs(params) != expect:
                raise VersionSkewError(
                    "swap_params: new params tree does not match the "
                    "serving structure (treedef/shape/dtype) — "
                    "publisher schema skew; swap rejected"
                )
            if self._flat is not None:
                new_params = self._own_store(self._flat.flatten(params))
            else:
                new_params = self._own_replicated(params)
            if rest is not None:
                if self._struct_specs(rest) != self._struct_specs(old[2]):
                    raise VersionSkewError(
                        "swap_params: new rest state does not match the "
                        "serving structure — swap rejected"
                    )
                new_rest = self._own_replicated(rest)
            else:
                new_rest = old[2]
            self._previous = old
            self._state = (int(version), new_params, new_rest)
            return old[0]

    def rollback(self) -> int:
        """Restore the retained previous version (bit-identical device
        arrays — they were never freed). Returns the version now
        serving; raises ``RuntimeError`` when there is nothing to roll
        back to."""
        with self._swap_lock:
            if self._previous is None:
                raise RuntimeError(
                    "rollback: no previous weight version retained"
                )
            bad = self._state
            self._state = self._previous
            # keep the rolled-back-from state referenced (not serving):
            # a post-mortem may want it, and re-rolling forward is the
            # controller's job, not an implicit ping-pong here
            self._previous = bad
            return self._state[0]

    # -- construction ------------------------------------------------------

    @classmethod
    def from_trainer(cls, trainer, **kwargs) -> "InferenceEngine":
        """Build an engine from a live trainer (``DataParallel``-shaped:
        ``sync_to_model``, ``mesh``, ``axis_name``; for a ``GANTrainer``
        pass one of ``sync_to_models()``'s modules to the constructor
        directly). This is the params-out-of-training-layout path:
        ``sync_to_model`` assembles the full parameter tree — under
        ``zero=True`` that is the ``parallel.zero.unshard_params``
        gather of the flat 1/world shards — and the engine re-replicates
        it for eval. The trainer keeps training; the engine owns copies
        on device.

        On a multi-device mesh this cold-start gather materializes the
        whole model in host memory (the ``max_replicated_bytes`` the
        sharding goldens keep pinned so it cannot silently grow) — use
        it for the FIRST engine build, then roll new versions in through
        the publication path (:mod:`tpu_syncbn.serve.publish`), whose
        on-mesh ``portable_redistribute`` + :meth:`swap_params` never
        leaves the device fabric; a deprecation-style warning below
        points there."""
        from tpu_syncbn.runtime import distributed as dist

        # composed layouts (anything beyond the 1-D data mesh) flow
        # through whole: the engine derives its batch spec from the
        # layout, and a param-sharding (fsdp) layout makes the engine
        # store flat shards instead of a replicated copy — the
        # satellite bugfix that shrinks the pinned max_replicated_bytes
        # for fsdp-composed trainers. Plain 1-D trainers keep the
        # byte-identical legacy replicated path.
        tl = getattr(trainer, "layout", None)
        if ("layout" not in kwargs and "mesh" not in kwargs
                and "axis_name" not in kwargs and tl is not None
                and tuple(tl.mesh.axis_names) != (DATA_AXIS,)):
            kwargs["layout"] = tl
        mesh = kwargs.get("mesh", trainer.mesh)
        if int(mesh.size) > 1:
            dist.get_logger("tpu_syncbn.serve").warning(
                "InferenceEngine.from_trainer on a %d-device mesh "
                "gathers the full parameter tree through host memory — "
                "a cold-start cost. For rolling weight updates use the "
                "zero-downtime publication path instead "
                "(tpu_syncbn.serve.publish.SwapController.swap_from_"
                "trainer: on-mesh redistribution + hot swap, no host "
                "gather, no restart).", int(mesh.size),
            )
        model = trainer.sync_to_model()
        if "layout" not in kwargs:
            kwargs.setdefault("mesh", trainer.mesh)
            kwargs.setdefault(
                "axis_name", getattr(trainer, "axis_name", DATA_AXIS)
            )
        return cls(model, **kwargs)

    # -- buckets / programs ------------------------------------------------

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """The smallest configured bucket that fits a global batch of
        ``n`` — the pad target. ``n`` beyond the largest bucket is a
        caller error (:meth:`predict` chunks before asking)."""
        if n < 1:
            raise ValueError(f"batch size must be >= 1, got {n}")
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket {self.max_bucket}"
        )

    def _struct_key(self, batch):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(batch)
        return treedef, tuple(
            (tuple(np.shape(l)[1:]), str(np.asarray(l).dtype)) for l in leaves
        )

    def _sharded_fwd(self):
        """The uncompiled sharded eval function ``(params, rest, batch)
        -> out``: replicated state in, batch split over the data axis
        (the batch's structure flows in through the argument, not the
        program text). This is what the audit layer traces
        (:mod:`tpu_syncbn.audit.jaxpr_audit`) — :meth:`_program` compiles
        exactly this, so the pinned contract is the shipped program."""
        from jax.sharding import PartitionSpec as P

        from tpu_syncbn import compat
        from tpu_syncbn.compat import shard_map
        from tpu_syncbn.parallel import collectives

        flat, shard_axis = self._flat, self._shard_axis

        def fwd(params, rest, b):
            if flat is not None:
                # flat 1/shard_world store: ONE all_gather per dtype
                # group rebuilds the tree inside the program — params
                # cross the wire once per call instead of living
                # replicated on every device
                params = flat.unflatten({
                    dt: collectives.all_gather(v, shard_axis, axis=0,
                                               tiled=True)
                    for dt, v in params.items()
                })
            model = compat.nnx_merge(self.graphdef, params, rest, copy=True)
            model.eval()
            return self._apply_fn(model, b)

        param_spec = (
            {dt: P(shard_axis) for dt in flat.shard_sizes}
            if flat is not None else P()
        )
        return shard_map(
            fwd,
            mesh=self.mesh,
            in_specs=(param_spec, P(), P(self.axis_name)),
            out_specs=P(self.axis_name),
            check_vma=self._check_vma,
        )

    def _bucket_struct(self, bucket: int, treedef, leafspecs):
        """``ShapeDtypeStruct`` pytree for a padded ``bucket``-sized batch
        of this structure, sharded like the real input."""
        import jax

        return jax.tree_util.tree_unflatten(treedef, [
            jax.ShapeDtypeStruct(
                (bucket,) + shape, np.dtype(dtype),
                sharding=self.batch_sharding,
            )
            for shape, dtype in leafspecs
        ])

    @staticmethod
    def _program_nbytes(compiled) -> int | None:
        """Best-effort compiled-program footprint from XLA's
        ``memory_analysis`` (temp + output + code size — the parts that
        scale with the bucket; arguments are the shared replicated
        params). ``None`` on backends that don't report one — the
        cache's entry bound still applies."""
        try:
            mem = compiled.memory_analysis()
        except Exception:
            return None
        if mem is None:
            return None
        total = 0
        for attr in ("temp_size_in_bytes", "output_size_in_bytes",
                     "generated_code_size_in_bytes"):
            v = getattr(mem, attr, None)
            if isinstance(v, int) and v > 0:
                total += v
        return total or None

    def _program(self, bucket: int, batch):
        """The AOT-compiled eval executable for ``bucket`` and this
        batch's structure (leaf shapes beyond the batch axis + dtypes).
        Cached through ``scan_driver.cached_program`` — size-aware LRU:
        at most ``MAX_CACHED_PROGRAMS`` distinct programs (and, when
        the engine was built with ``program_cache_bytes``, at most that
        many measured bytes) stay live; least-recently-used evicted
        first."""
        import jax

        from tpu_syncbn.obs import telemetry
        from tpu_syncbn.parallel import scan_driver

        treedef, leafspecs = self._struct_key(batch)
        key = (bucket, treedef, leafspecs)

        def build():
            import time

            sharded = self._sharded_fwd()
            sds = self._bucket_struct(bucket, treedef, leafspecs)
            t0 = time.perf_counter()
            with telemetry.timed("serve.compile_s"):
                compiled = jax.jit(sharded).lower(
                    self._params, self._rest, sds
                ).compile()
            telemetry.count("serve.compiles")
            if self._model_labels is not None:
                telemetry.observe("serve.compile_s",
                                  time.perf_counter() - t0,
                                  labels=self._model_labels)
                telemetry.count("serve.compiles",
                                labels=self._model_labels)
            # int bump on the GIL, read only by stats(); _swap_lock
            # guards the version triple, not the program cache
            self._programs_compiled += 1  # audit: ok[unlocked_shared_state]
            return compiled

        return scan_driver.cached_program(
            self._programs, key, build, size_of=self._program_nbytes
        )

    def warm(self, example_batch) -> None:
        """AOT-compile every bucket's program for ``example_batch``'s
        structure (any leading-axis length), off the request path — so
        the first real request of each bucket is an execute, not a
        compile."""
        for b in self.buckets:
            self._program(b, example_batch)

    def stats(self) -> dict:
        """Program-cache accounting for the serve block / monitoring:
        configured buckets, total programs ever compiled, programs
        currently live (FIFO bound), and the cache's lifetime
        hits/misses/evictions (hit rate = hits / (hits + misses))."""
        return {
            "buckets": list(self.buckets),
            "programs_compiled": self._programs_compiled,
            "programs_live": len(self._programs),
            "program_cache": self._programs.stats(),
            "version": self.version,
            "previous_version": self.previous_version,
        }

    def health(self) -> dict:
        """Compact JSON-ready health summary for readiness probes (the
        batcher folds it into its ``/readyz`` detail): bucket coverage
        and program-cache state — a climbing ``compiled`` with a capped
        ``live`` under steady traffic means shape churn is recompiling
        on the request path."""
        return {
            "buckets": list(self.buckets),
            "programs_live": len(self._programs),
            "programs_compiled": self._programs_compiled,
            "version": self.version,
        }

    # -- execution ---------------------------------------------------------

    def _run_one(self, batch, n: int):
        import time

        import jax

        from tpu_syncbn.obs import stepstats as obs_stepstats
        from tpu_syncbn.obs import telemetry

        bucket = self.bucket_for(n)
        pad = bucket - n

        def pad_leaf(l):
            a = np.asarray(l)
            if pad == 0:
                return a
            return np.concatenate(
                [a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0
            )

        # ONE atomic read pins this call's weight version: a concurrent
        # swap_params() replaces self._state but cannot touch the triple
        # already captured here — in-flight batches finish on the
        # version they started on (tests/test_publish.py pins this)
        _, params, rest = self._state
        fn = self._program(bucket, batch)
        padded = jax.tree_util.tree_map(pad_leaf, batch)
        # level gauge, not set(): concurrent callers each inc/dec their
        # own contribution atomically (obs.telemetry.Gauge.inc)
        telemetry.inc_gauge("serve.inflight")
        if self._model_labels is not None:
            telemetry.inc_gauge("serve.inflight",
                                labels=self._model_labels)
        t0 = time.perf_counter()
        try:
            with obs_stepstats.timed_span(
                "serve.infer", "serve.infer_s", n=n, bucket=bucket
            ):
                dev = jax.device_put(padded, self.batch_sharding)
                out = fn(params, rest, dev)
                # gather: host numpy, padding sliced back off — the
                # engine's callers (the batcher's response path) want
                # settled bytes
                return jax.tree_util.tree_map(
                    lambda a: np.asarray(a)[:n], out
                )
        finally:
            if self._model_labels is not None:
                telemetry.observe("serve.infer_s",
                                  time.perf_counter() - t0,
                                  labels=self._model_labels)
                telemetry.inc_gauge("serve.inflight", -1,
                                    labels=self._model_labels)
            telemetry.inc_gauge("serve.inflight", -1)

    def predict(self, batch):
        """Run the eval forward on a host batch pytree (leading axis =
        global batch). Pads to the nearest bucket, executes that
        bucket's compiled program sharded over the data axis, returns
        host numpy outputs of the *original* length. Batches beyond the
        largest bucket are chunked through it."""
        import jax

        n = _leading_dim(batch)
        if n <= self.max_bucket:
            return self._run_one(batch, n)
        outs = []
        for off in range(0, n, self.max_bucket):
            take = min(self.max_bucket, n - off)
            part = jax.tree_util.tree_map(
                lambda a: np.asarray(a)[off:off + take], batch
            )
            outs.append(self._run_one(part, take))
        return jax.tree_util.tree_map(
            lambda *ls: np.concatenate(ls, axis=0), *outs
        )

    __call__ = predict
