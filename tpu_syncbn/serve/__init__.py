"""Serving subsystem: dynamic-batching inference on the trained model.

The first non-training subsystem in the codebase (ROADMAP north star:
"serves heavy traffic from millions of users"). Two layers:

* :mod:`tpu_syncbn.serve.engine` — :class:`InferenceEngine`: params
  restored out of their training layout once (ZeRO flat shards gathered
  via ``parallel.zero.unshard_params``, then re-replicated), model
  pinned in eval mode (BN on running stats — collective-free, hence
  embarrassingly data-parallel), and a FIFO-bounded set of bucketed
  AOT-compiled eval programs sharded over the ``data`` axis.
* :mod:`tpu_syncbn.serve.batcher` — :class:`DynamicBatcher`: bounded
  request queue with a ``max_batch``/``max_wait_ms`` admission policy,
  pad-to-bucket coalescing, queue-full rejection (backpressure), and
  graceful drain wired to the resilience layer's
  :class:`~tpu_syncbn.runtime.resilience.PreemptionGuard`.
* :mod:`tpu_syncbn.serve.admission` — the overload-robustness layer:
  request deadlines with earliest-deadline-first dispatch and
  predicted-completion load shedding (:class:`AdmissionController`,
  :class:`LatencyEstimator`), plus a consecutive-failure
  :class:`CircuitBreaker` with PR 1 deterministic-jitter backoff
  half-open probes (docs/RESILIENCE.md "Serving failure modes").
* :mod:`tpu_syncbn.serve.loadgen` — open-loop Poisson/trace-driven
  load generation (:class:`OpenLoopLoadGen`): the offered-load-sweep
  harness ``bench --serve`` uses to prove graceful degradation past
  saturation (bounded p99, rising sheds — never queueing collapse).
* :mod:`tpu_syncbn.serve.publish` — zero-downtime weight publication:
  :class:`SwapController` hot-swaps manifest-verified published
  versions (or a live trainer's params, re-sharded on the mesh via
  ``parallel.redistribute``) into a running engine with drain,
  memwatch-bounded double-buffering, and automatic rollback
  (docs/RESILIENCE.md "Zero-downtime publication").

Quickstart::

    from tpu_syncbn import serve

    engine = serve.InferenceEngine.from_trainer(dp, buckets=(8, 32, 128))
    engine.warm(example_batch)                     # AOT-compile buckets
    with serve.DynamicBatcher(engine, max_batch=128,
                              max_wait_ms=5) as batcher:
        fut = batcher.submit(x[i:i + 1])           # per-request future
        logits = fut.result()

``bench.py --serve`` runs a closed-loop offered-load sweep against this
stack and reports throughput / p50-p99 latency / batch-fill ratio in the
schema-pinned ``serve`` block (docs/PERFORMANCE.md "Serving";
docs/OBSERVABILITY.md for the ``serve.*`` metric schemas).
"""

from tpu_syncbn.parallel.zero import unshard_params  # noqa: F401
from tpu_syncbn.serve.admission import (  # noqa: F401
    AdmissionController,
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    LatencyEstimator,
    RejectedError,
)
from tpu_syncbn.serve.batcher import DynamicBatcher  # noqa: F401
from tpu_syncbn.serve.engine import (  # noqa: F401
    InferenceEngine,
    VersionSkewError,
)
from tpu_syncbn.serve.publish import (  # noqa: F401
    PublicationError,
    SwapAbortedError,
    SwapController,
)
from tpu_syncbn.serve.loadgen import (  # noqa: F401
    LoadReport,
    OpenLoopLoadGen,
    poisson_arrivals,
    trace_arrivals,
)

__all__ = [
    "InferenceEngine",
    "DynamicBatcher",
    "RejectedError",
    "DeadlineExceededError",
    "CircuitOpenError",
    "CircuitBreaker",
    "AdmissionController",
    "LatencyEstimator",
    "OpenLoopLoadGen",
    "LoadReport",
    "poisson_arrivals",
    "trace_arrivals",
    "unshard_params",
    "SwapController",
    "PublicationError",
    "SwapAbortedError",
    "VersionSkewError",
]
