"""Zero-downtime weight publication: manifest-verified versioned hot
swap with drain, rollback, and a bounded transient footprint.

The stack trains continuously (``runtime.resilience.ResilientLoop``) and
serves under overload (``serve.batcher`` + ``serve.admission``); this
module joins them WITHOUT the cold start of
``InferenceEngine.from_trainer`` (full host gather + engine rebuild +
recompile). A :class:`SwapController` rolls a new weight version into a
*running* engine:

* **sources** — :meth:`SwapController.swap_from_trainer` re-shards a
  live trainer's params train-layout → serve-layout entirely on the
  mesh (:func:`tpu_syncbn.parallel.redistribute.portable_redistribute`
  under ZeRO — no host gather, golden-pinned as the
  ``serve.redistribute`` audit contract);
  :meth:`SwapController.swap_from_publication` loads a
  manifest-verified published version from disk
  (:func:`tpu_syncbn.utils.checkpoint.load_published`) — a truncated or
  bit-flipped publication is **rejected** (the old version keeps
  serving), and a structurally skewed one is rejected before
  deserialization (:class:`~tpu_syncbn.utils.checkpoint.
  PublicationSkewError`).
* **double-buffer** — the engine holds old and new state simultaneously
  for the instant of the swap (``InferenceEngine.swap_params``'s atomic
  triple); in-flight batches finish on the version they started on, the
  next request runs the new one, and the compiled bucket programs are
  reused unchanged (state is a runtime argument). The transient
  footprint is bounded by the installed ``memwatch`` contract: a swap
  whose projected usage would cross the pressure threshold fires
  ``mem_pressure`` and **aborts cleanly** instead of OOMing serving.
* **drain / readiness** — the controller registers a ``/readyz`` hook
  (``health_name``, default ``publication``) that flips not-ready for
  exactly the critical window (pre-commit → probe-settled); a
  :class:`~tpu_syncbn.runtime.resilience.PreemptionGuard` that has
  fired aborts a not-yet-committed swap and cuts the probe window of a
  committed one short, so a draining process never wedges mid-swap.
* **rollback** — a failed post-swap health probe (canary batch raising,
  or the serving circuit breaker opening within ``probe_window_s``)
  rolls back to the retained previous version — bit-identical device
  arrays, never freed during the window.
* **observability** — ``serve.version.active`` / ``.previous`` gauges,
  ``serve.swap_s`` histogram, ``serve.swaps_total`` /
  ``serve.rollbacks_total`` / ``serve.swap_rejected_total`` counters;
  every swap, rejection, and rollback lands in the flight recorder's
  serve ring AND dumps a ``weight_swap`` incident bundle (version,
  trigger, timing); ``/statusz`` renders the publication section.

The deterministic chaos matrix over this path (corrupt publication,
SIGTERM mid-swap, crash-on-first-new-version-batch, version skew,
memwatch abort) lives in :mod:`tpu_syncbn.testing.faults` +
tests/test_publish.py; ``bench.py --serve`` measures the swap under
open-loop load in the schema-pinned ``publish`` block.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from tpu_syncbn.obs import flightrec, telemetry

__all__ = [
    "SWAP_PHASES",
    "PublicationError",
    "SwapAbortedError",
    "SwapController",
]

#: The swap's phase sequence, in order. ``phase_hook(phase)`` fires at
#: each boundary — the deterministic injection seam the fault harness
#: keys on (``testing.faults.signal_at_phase``).
SWAP_PHASES = ("verify", "preflight", "not_ready", "commit", "probe",
               "ready")


def _publish_version(mode: str, value: float) -> None:
    """Publish the ``serve.version{mode=...}`` gauge (``mode`` is
    ``active`` or ``previous``), mirroring the legacy flat
    ``serve.version.<mode>`` name behind a DeprecationWarning so
    pre-label dashboards keep resolving."""
    telemetry.set_gauge("serve.version", value, labels={"mode": mode})
    legacy = f"serve.version.{mode}"
    telemetry.warn_deprecated_name(
        legacy, telemetry.labeled_name("serve.version", {"mode": mode})
    )
    telemetry.set_gauge(legacy, value)


class PublicationError(RuntimeError):
    """A weight swap could not be performed; serving state untouched."""


class SwapAbortedError(PublicationError):
    """The swap aborted cleanly before commit (preemption drain, or the
    projected double-buffer would cross the memwatch pressure
    threshold). The engine still serves the pre-swap version."""


class SwapController:
    """Orchestrates versioned hot swaps on one
    :class:`~tpu_syncbn.serve.engine.InferenceEngine` (duck-typed:
    ``swap_params`` / ``rollback`` / ``version`` / ``previous_version``
    — the fault harness swaps stand-ins in).

    ``batcher`` (optional) donates its circuit breaker and preemption
    guard — the breaker is the post-swap health signal (it opens when
    the NEW version's engine calls fail, which is exactly the automatic
    rollback trigger), the guard is the drain signal. Both can also be
    passed explicitly. ``probe_window_s`` bounds how long a committed
    swap watches the breaker before declaring the new version healthy
    (0 = only the synchronous ``canary`` probe, no wait).
    ``phase_hook`` is called with each :data:`SWAP_PHASES` name as the
    swap crosses it (fault-injection seam; exceptions from the hook
    propagate like real faults at that point)."""

    def __init__(
        self,
        engine,
        *,
        batcher=None,
        guard=None,
        breaker=None,
        health_name: str = "publication",
        probe_window_s: float = 0.0,
        probe_poll_s: float = 0.05,
        phase_hook: Callable[[str], None] | None = None,
    ):
        from tpu_syncbn.obs import server as obs_server

        self.engine = engine
        self._guard = guard if guard is not None else (
            getattr(batcher, "guard", None) if batcher is not None else None
        )
        self._breaker = breaker if breaker is not None else (
            getattr(batcher, "breaker", None) if batcher is not None
            else None
        )
        if probe_window_s < 0:
            raise ValueError(
                f"probe_window_s must be >= 0, got {probe_window_s}"
            )
        self.probe_window_s = float(probe_window_s)
        self.probe_poll_s = float(probe_poll_s)
        self._phase_hook = phase_hook
        self._health_name = str(health_name)
        self._swapping = False
        # RLock: the reject/abort accounting runs both under swap()'s
        # hold and bare (swap_from_publication rejects before swapping)
        self._lock = threading.RLock()
        self.swaps = 0
        self.rollbacks = 0
        self.rejected = 0
        self.last: dict | None = None
        _publish_version("active", int(getattr(engine, "version", 0)))
        obs_server.register_readiness(self._health_name, self.readiness)
        self._registered = True

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        from tpu_syncbn.obs import server as obs_server

        if self._registered:
            obs_server.unregister_readiness(self._health_name)
            self._registered = False

    def __enter__(self) -> "SwapController":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- readiness ---------------------------------------------------------

    def readiness(self) -> tuple[bool, dict]:
        """The ``/readyz`` contribution (``health_name`` hook): NOT
        ready exactly while a swap is inside its critical window
        (pre-commit → probe settled) — the documented window a balancer
        should route around — ready otherwise, with the live version
        pair and swap/rollback counts as detail."""
        swapping = self._swapping
        return not swapping, {
            "swapping": swapping,
            "version": int(getattr(self.engine, "version", 0)),
            "previous_version": getattr(self.engine, "previous_version",
                                        None),
            "swaps": self.swaps,
            "rollbacks": self.rollbacks,
            "rejected": self.rejected,
        }

    # -- internals ---------------------------------------------------------

    def _phase(self, name: str) -> None:
        if self._phase_hook is not None:
            self._phase_hook(name)

    def _preempted(self) -> bool:
        return self._guard is not None and bool(self._guard.preempted)

    def _reject(self, *, version, source: str, reason: str,
                exc: BaseException | None = None) -> None:
        """Account a rejected publication/swap (serving untouched)."""
        with self._lock:
            self.rejected += 1
        telemetry.count("serve.swap_rejected_total")
        detail = {
            "outcome": "rejected", "version": version, "source": source,
            "reason": reason,
            "serving_version": int(getattr(self.engine, "version", 0)),
        }
        flightrec.record_serve("weight_swap", **detail)
        flightrec.trigger("weight_swap", detail)

    def _preflight_memory(self, version, source: str) -> None:
        """The memwatch double-buffer bound: with a sampler installed
        AND a pinned contract, project current usage + the incoming
        replicated state's bytes against the pressure threshold; a swap
        that would cross it fires ``mem_pressure`` and aborts cleanly
        (the alternative is the allocator OOMing live traffic
        mid-swap)."""
        from tpu_syncbn.obs import memwatch

        sampler = memwatch.get()
        if sampler is None:
            return
        contract = sampler.contract().get("bytes_per_device")
        threshold = sampler.pressure_threshold
        if not contract or threshold is None:
            return
        nbytes = getattr(self.engine, "params_nbytes", None)
        if not callable(nbytes):
            return
        incoming = int(nbytes())
        reading = sampler.sample()
        used = int(reading.get("bytes_in_use") or 0)
        projected = (used + incoming) / contract
        if projected <= threshold:
            return
        detail = {
            "outcome": "aborted", "version": version, "source": source,
            "reason": "mem_pressure",
            "bytes_in_use": used,
            "double_buffer_bytes": incoming,
            "projected_frac": round(projected, 6),
            "threshold": threshold,
            "contract_bytes_per_device": contract,
        }
        flightrec.record_serve("weight_swap", **detail)
        flightrec.trigger("mem_pressure", detail)
        telemetry.count("serve.swap_rejected_total")
        with self._lock:
            self.rejected += 1
        raise SwapAbortedError(
            f"swap to v{version} would put projected device usage at "
            f"{projected:.2f}x the memwatch contract (threshold "
            f"{threshold}) — double-buffer of {incoming} B does not "
            "fit; aborting with the old version serving"
        )

    def _probe(self, canary) -> str | None:
        """Post-swap health probe. Returns a failure reason, or None
        when the new version looks healthy: first the synchronous
        canary (a batch through the new version — an engine that cannot
        answer it is dead on arrival), then the circuit-breaker watch —
        the breaker opening inside ``probe_window_s`` means real
        traffic is failing on the new version."""
        if canary is not None:
            try:
                self.engine.predict(canary)
            except Exception as e:
                return f"canary failed: {type(e).__name__}: {e}"
        breaker = self._breaker
        if breaker is None or self.probe_window_s <= 0:
            return None
        deadline = time.monotonic() + self.probe_window_s
        while time.monotonic() < deadline:
            if getattr(breaker, "state", None) == "open":
                return "circuit breaker opened on the new version"
            if self._preempted():
                return None  # draining: stop watching, keep the swap
            time.sleep(min(self.probe_poll_s,
                           max(0.0, deadline - time.monotonic())))
        return None

    # -- the swap ----------------------------------------------------------

    def swap(self, params, rest=None, *, version: int | None = None,
             source: str = "direct", canary=None) -> dict:
        """Roll ``params`` (+ ``rest``) in as the next weight version.
        Returns a result dict (``outcome`` ``"swapped"`` or
        ``"rolled_back"``, versions, phase timings). Raises
        :class:`SwapAbortedError` on a clean pre-commit abort
        (preemption drain / memwatch bound) and
        :class:`~tpu_syncbn.serve.engine.VersionSkewError` on a
        structure mismatch — in every raising case the engine still
        serves its pre-swap version."""
        from tpu_syncbn.serve.engine import VersionSkewError

        with self._lock:
            t0 = time.perf_counter()
            if version is None:
                version = int(getattr(self.engine, "version", 0)) + 1
            version = int(version)
            self._phase("verify")
            if self._preempted():
                self._reject(version=version, source=source,
                             reason="preempted")
                raise SwapAbortedError(
                    "preemption signaled: draining, not starting a swap"
                )
            self._phase("preflight")
            self._preflight_memory(version, source)
            self._swapping = True  # /readyz critical window opens
            try:
                self._phase("not_ready")
                if self._preempted():
                    self._reject(version=version, source=source,
                                 reason="preempted")
                    raise SwapAbortedError(
                        "preemption signaled mid-swap before commit: "
                        "draining with the old version serving"
                    )
                self._phase("commit")
                try:
                    old = self.engine.swap_params(
                        params, rest, version=version
                    )
                except VersionSkewError:
                    self._reject(version=version, source=source,
                                 reason="version_skew")
                    raise
                commit_s = time.perf_counter() - t0
                self._phase("probe")
                failure = self._probe(canary)
                if failure is not None:
                    restored = self.engine.rollback()
                    self.rollbacks += 1
                    swap_s = time.perf_counter() - t0
                    telemetry.count("serve.rollbacks_total")
                    _publish_version("active", restored)
                    _publish_version("previous", version)
                    result = {
                        "outcome": "rolled_back", "version": restored,
                        "failed_version": version, "source": source,
                        "reason": failure,
                        "commit_s": round(commit_s, 6),
                        "swap_s": round(swap_s, 6),
                    }
                    flightrec.record_serve("weight_swap", **result)
                    flightrec.trigger("weight_swap", result)
                    self.last = result
                    return result
            finally:
                self._phase("ready")
                self._swapping = False  # critical window closes
            swap_s = time.perf_counter() - t0
            self.swaps += 1
            telemetry.count("serve.swaps_total")
            telemetry.observe("serve.swap_s", swap_s)
            _publish_version("active", version)
            _publish_version("previous", old)
            result = {
                "outcome": "swapped", "version": version,
                "previous_version": old, "source": source,
                "commit_s": round(commit_s, 6),
                "swap_s": round(swap_s, 6),
            }
            flightrec.record_serve("weight_swap", **result)
            flightrec.trigger("weight_swap", result)
            self.last = result
            return result

    def rollback(self, *, reason: str = "manual") -> dict:
        """Roll serving back to the retained previous version (the
        operator's big red button; the probe path calls the same engine
        primitive). Returns a result dict."""
        with self._lock:
            t0 = time.perf_counter()
            bad = int(getattr(self.engine, "version", 0))
            restored = self.engine.rollback()
            self.rollbacks += 1
            telemetry.count("serve.rollbacks_total")
            _publish_version("active", restored)
            _publish_version("previous", bad)
            result = {
                "outcome": "rolled_back", "version": restored,
                "failed_version": bad, "source": "manual",
                "reason": reason,
                "swap_s": round(time.perf_counter() - t0, 6),
            }
            flightrec.record_serve("weight_swap", **result)
            flightrec.trigger("weight_swap", result)
            self.last = result
            return result

    # -- sources -----------------------------------------------------------

    def swap_from_trainer(self, trainer, *, version: int | None = None,
                          canary=None) -> dict:
        """Hot-swap straight from a live trainer on the same mesh. Under
        ``zero=True`` the flat 1/world shards are re-sharded to the
        replicated serving layout ON the mesh
        (:func:`~tpu_syncbn.parallel.redistribute.portable_redistribute`
        — no host gather; the ``serve.redistribute`` golden pins the
        wire cost); otherwise the trainer's replicated param store is
        used as-is. BN running stats ride along via the trainer's
        ``rest`` state."""
        if getattr(trainer, "zero", False):
            from tpu_syncbn.parallel.redistribute import (
                portable_redistribute,
            )

            params = portable_redistribute(
                trainer._layout, trainer._param_store, trainer.mesh,
                # composed layouts shard over ONE axis (fsdp); the stat
                # axis tuple is not the redistribution axis
                getattr(trainer, "_shard_axis", None)
                or getattr(trainer, "axis_name", "data"),
            )
        else:
            params = trainer._param_store
        return self.swap(params, getattr(trainer, "rest", None),
                         version=version, source="trainer", canary=canary)

    def swap_from_publication(self, directory: str, *,
                              canary=None) -> dict:
        """Load the currently published weight version
        (:func:`tpu_syncbn.utils.checkpoint.load_published`) and swap it
        in. Verification is the gate: a corrupt publication (truncated,
        bit-flipped, manifest missing) or a structurally skewed one is
        REJECTED — accounted in ``serve.swap_rejected_total`` and the
        flight recorder — and the exception propagates with the old
        version still serving; zero requests ever touch the bad
        bytes."""
        from tpu_syncbn.utils import checkpoint as ckpt

        get_template = getattr(self.engine, "param_template", None)
        template = {"params": get_template() if get_template is not None
                    else self.engine._params,
                    "rest": self.engine._rest}
        expect = ckpt.tree_structure_hash(
            __import__("jax").device_get(ckpt._purify(template))
        )
        try:
            tree, version = ckpt.load_published(
                directory, template, expect_tree_hash=expect
            )
        except ckpt.PublicationSkewError:
            self._reject(version=ckpt.published_version(directory),
                         source="publication", reason="version_skew")
            raise
        except (FileNotFoundError, ckpt.CheckpointCorruptError):
            self._reject(version=ckpt.published_version(directory),
                         source="publication", reason="corrupt")
            raise
        return self.swap(tree["params"], tree["rest"], version=version,
                         source="publication", canary=canary)
