"""Detection data pipeline: padded-GT datasets and collation for the
RetinaNet capability config (BASELINE.json config 4).

Detection batches need static shapes on TPU (XLA recompiles on shape
change), so ground truth is padded to a fixed ``max_boxes`` per image with
a validity mask — the exact contract ``models.RetinaNet.loss`` consumes.
COCO-style annotations on disk load through :class:`CocoDetectionDataset`
when present; a deterministic synthetic generator stands in otherwise
(zero-egress environment).
"""

from __future__ import annotations

import json
import os

import numpy as np

from tpu_syncbn.data.dataset import Dataset


def pad_ground_truth(
    boxes: np.ndarray, labels: np.ndarray, max_boxes: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad (N,4) boxes / (N,) labels to ``max_boxes`` with a validity mask;
    excess boxes are truncated (torchvision keeps them — TPU static shapes
    force the cap; choose max_boxes above the dataset's true maximum)."""
    n = min(len(boxes), max_boxes)
    out_boxes = np.zeros((max_boxes, 4), np.float32)
    out_labels = np.zeros((max_boxes,), np.int32)
    valid = np.zeros((max_boxes,), bool)
    out_boxes[:n] = boxes[:n]
    out_labels[:n] = labels[:n]
    valid[:n] = True
    return out_boxes, out_labels, valid


class SyntheticDetectionDataset(Dataset):
    """Deterministic *learnable* synthetic detection samples:
    ``(image HWC, boxes (M,4), labels (M,), valid (M,))`` with 1..max_boxes
    random boxes per image — shapes ready for RetinaNet.loss.

    Each box region is painted with a class-specific color (a fixed
    palette keyed on the label) over a noise background, so localization
    and classification are actually learnable from pixels — a detector
    can be trained to nonzero mAP on this data, which is what the
    detection A/B's task-metric readout needs. ``noise`` scales the
    additive pixel noise (task difficulty knob); ``box_frac`` bounds box
    side length as a fraction of the image side (the default 10-30%
    sits below RetinaNet's smallest default anchor at 64x64 — pass
    e.g. ``(0.4, 0.7)`` for boxes the anchor grid can match at IoU>=0.5).

    Occlusion caveat: overlapping boxes are painted in order, so a later
    box overwrites an earlier box's class-colored pixels while the
    occluded ground truth is kept. That is bounded label noise at the
    default ``max_boxes=2`` but grows with ``max_boxes`` — it caps the
    AP any detector (or the A/B's val_map instrument) can reach on this
    data. Painting is deliberately left bit-identical across versions
    because recorded A/B artifacts key on the exact pixel stream."""

    def __init__(
        self,
        length: int = 256,
        image_size: tuple[int, int] = (64, 64),
        num_classes: int = 5,
        max_boxes: int = 8,
        seed: int = 0,
        noise: float = 0.3,
        box_frac: tuple[float, float] = (0.1, 0.3),
    ):
        self.length = length
        self.image_size = image_size
        self.num_classes = num_classes
        self.max_boxes = max_boxes
        self.seed = seed
        self.noise = noise
        self.box_frac = box_frac
        # class palette: fixed across instances with the same num_classes
        # (train and held-out sets must mean the same thing by a label)
        self.palette = np.random.RandomState(12345).uniform(
            -1.5, 1.5, (num_classes, 3)
        ).astype(np.float32)

    def __len__(self):
        return self.length

    def __getitem__(self, idx):
        if not 0 <= idx < self.length:
            raise IndexError(idx)
        rng = np.random.RandomState((self.seed * 999_983 + idx) % (2**31))
        h, w = self.image_size
        image = self.noise * rng.randn(h, w, 3).astype(np.float32)
        n = rng.randint(1, self.max_boxes + 1)
        lo, hi = self.box_frac
        x1 = rng.uniform(0, w * (1 - lo), n)
        y1 = rng.uniform(0, h * (1 - lo), n)
        bw = rng.uniform(w * lo, w * hi, n)
        bh = rng.uniform(h * lo, h * hi, n)
        boxes = np.stack(
            [x1, y1, np.minimum(x1 + bw, w), np.minimum(y1 + bh, h)], axis=1
        ).astype(np.float32)
        labels = rng.randint(0, self.num_classes, n).astype(np.int32)
        for (bx1, by1, bx2, by2), lab in zip(boxes, labels):
            # clamp into the canvas: rounding can push a box start to the
            # image edge (x1 can approach w for small box_frac minima),
            # and the painted block's shape must match its slice exactly
            ix1 = min(int(round(bx1)), w - 1)
            iy1 = min(int(round(by1)), h - 1)
            ix2 = min(max(int(round(bx2)), ix1 + 1), w)
            iy2 = min(max(int(round(by2)), iy1 + 1), h)
            image[iy1:iy2, ix1:ix2] = (
                self.palette[lab]
                + self.noise * rng.randn(iy2 - iy1, ix2 - ix1, 3)
            ).astype(np.float32)
        return (image,) + pad_ground_truth(boxes, labels, self.max_boxes)


class CocoDetectionDataset(Dataset):
    """COCO-format annotations + real images (or a pre-decoded store).

    ``annotation_file`` is standard COCO instances JSON. Images load from
    ``image_root``: the actual ``file_name`` (JPEG/PNG, PIL decode — the
    real-COCO path, reference ``README.md:76-91`` step 5) when present,
    else ``{file_name}.npy`` (HWC float32 from a one-off pre-decode
    pass). Category ids are densified to [0, K).

    ``image_size=(H, W)`` resizes every image to a fixed shape (bilinear)
    and scales its boxes to match — TPU static-shape requirement for
    batched detection training.
    """

    def __init__(self, annotation_file: str, image_root: str, *,
                 max_boxes: int = 100,
                 image_size: tuple[int, int] | None = None):
        with open(annotation_file) as f:
            coco = json.load(f)
        self.image_root = image_root
        self.max_boxes = max_boxes
        self.image_size = image_size
        cats = sorted(c["id"] for c in coco.get("categories", []))
        self.cat_to_dense = {c: i for i, c in enumerate(cats)}
        self.num_classes = len(cats)
        anns_by_img: dict[int, list] = {}
        for a in coco.get("annotations", []):
            anns_by_img.setdefault(a["image_id"], []).append(a)
        self.entries = []
        for img in coco.get("images", []):
            anns = anns_by_img.get(img["id"], [])
            boxes = np.asarray(
                [
                    [a["bbox"][0], a["bbox"][1],
                     a["bbox"][0] + a["bbox"][2], a["bbox"][1] + a["bbox"][3]]
                    for a in anns
                ],
                np.float32,
            ).reshape(-1, 4)
            labels = np.asarray(
                [self.cat_to_dense[a["category_id"]] for a in anns], np.int32
            )
            self.entries.append((img["file_name"], boxes, labels))

    def __len__(self):
        return len(self.entries)

    def __getitem__(self, idx):
        file_name, boxes, labels = self.entries[idx]
        raw = os.path.join(self.image_root, file_name)
        if os.path.exists(raw):
            from tpu_syncbn.data.image_folder import decode_image

            image = decode_image(raw).astype(np.float32) / 255.0
        else:
            image = np.load(raw + ".npy").astype(np.float32)
        if self.image_size is not None:
            h, w = image.shape[:2]
            th, tw = self.image_size
            if (h, w) != (th, tw):
                from tpu_syncbn.data.transforms import _resize_bilinear

                image = _resize_bilinear(image, (th, tw))
                boxes = boxes * np.asarray(
                    [tw / w, th / h, tw / w, th / h], np.float32
                )
        return (image,) + pad_ground_truth(boxes, labels, self.max_boxes)
