"""Host-side image transforms (numpy) — the augmentation work the
reference's 8 DataLoader workers do per sample (``README.md:87``).
torchvision-transform-style API so the typical recipe user's pipeline
ports directly; all operate on HWC numpy arrays.

Randomness contract: each random transform draws from its own generator —
pass ``rng=`` (a shared ``np.random.RandomState`` you manage) or ``seed=``
(int) for reproducibility; by default a fresh entropy-seeded generator is
used, so composed transforms are independent. Draws are lock-protected,
so transforms are safe under the threaded DataLoader; with
``num_workers=0`` a seeded pipeline is bit-reproducible run to run, with
worker threads the *batch order* stays deterministic but the augmentation
draw order follows thread scheduling (same tradeoff as torch's workers
without per-worker seeding).
"""

from __future__ import annotations

import threading
from typing import Callable, Sequence

import numpy as np


class _Draws:
    """Lock-protected RandomState shared safely across loader threads.

    Picklable (for process workers): the lock is dropped and recreated;
    the RNG state pickles with numpy. Each worker process then owns a
    COPY of the generator — reseed via ``DataLoader(worker_init_fn=...)``
    if per-worker decorrelated augmentation draws matter (same caveat as
    torch's per-worker seeding)."""

    def __init__(self, rng: np.random.RandomState | None, seed: int | None):
        if rng is not None:
            self._rng = rng
        else:
            self._rng = np.random.RandomState(seed)  # None → OS entropy
        self._lock = threading.Lock()

    def __getstate__(self):
        return {"_rng": self._rng}

    def __setstate__(self, state):
        self._rng = state["_rng"]
        self._lock = threading.Lock()

    def reseed(self, seed: int) -> None:
        with self._lock:
            self._rng = np.random.RandomState(seed)

    def rand(self) -> float:
        with self._lock:
            return float(self._rng.rand())

    def randint(self, n: int) -> int:
        with self._lock:
            return int(self._rng.randint(n))

    def uniform(self, lo: float, hi: float) -> float:
        with self._lock:
            return float(self._rng.uniform(lo, hi))


class Compose:
    def __init__(self, transforms: Sequence[Callable]):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x

    def reseed(self, seed: int) -> None:
        """Reseed every random child transform with a seed derived from
        ``seed`` and its position — THE public hook for per-worker
        augmentation decorrelation in a process-worker ``worker_init_fn``
        (each spawn worker inherits an identical pickled RNG state)::

            def init(wid):
                tdata.get_worker_info().dataset.transform.reseed(1000 + wid)
        """
        for i, t in enumerate(self.transforms):
            if hasattr(t, "reseed"):
                t.reseed(seed * 1_000_003 + i)


class RandomHorizontalFlip:
    def __init__(self, p: float = 0.5, *, rng=None, seed: int | None = None):
        self.p = p
        self._draws = _Draws(rng, seed)

    def reseed(self, seed: int) -> None:
        self._draws.reseed(seed)

    def __call__(self, x):
        if self._draws.rand() < self.p:
            return np.ascontiguousarray(x[:, ::-1])
        return x


class RandomCrop:
    """Pad-then-crop (the CIFAR recipe: pad 4, crop 32). Default padding is
    zero-fill, matching torchvision's ``RandomCrop(32, padding=4)``;
    ``padding_mode="reflect"`` opts into reflect padding."""

    def __init__(self, size: int, padding: int = 4, *,
                 padding_mode: str = "constant",
                 rng=None, seed: int | None = None):
        self.size = size
        self.padding = padding
        self.padding_mode = padding_mode
        self._draws = _Draws(rng, seed)

    def reseed(self, seed: int) -> None:
        self._draws.reseed(seed)

    def __call__(self, x):
        p = self.padding
        kw = {"mode": self.padding_mode}
        if self.padding_mode == "constant":
            kw["constant_values"] = 0
        padded = np.pad(x, ((p, p), (p, p), (0, 0)), **kw)
        if padded.shape[0] < self.size or padded.shape[1] < self.size:
            raise ValueError(
                f"crop size {self.size} larger than padded input "
                f"{padded.shape[:2]}"
            )
        i = self._draws.randint(padded.shape[0] - self.size + 1)
        j = self._draws.randint(padded.shape[1] - self.size + 1)
        return padded[i : i + self.size, j : j + self.size]


class RandomResizedCrop:
    """ImageNet-style scale/aspect jitter crop + resize (bilinear by
    default, matching torchvision)."""

    def __init__(self, size: int, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 *, interpolation: str = "bilinear",
                 rng=None, seed: int | None = None):
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation
        self._draws = _Draws(rng, seed)

    def reseed(self, seed: int) -> None:
        self._draws.reseed(seed)

    def __call__(self, x):
        h, w = x.shape[:2]
        area = h * w
        for _ in range(10):
            target = self._draws.uniform(*self.scale) * area
            ar = np.exp(
                self._draws.uniform(np.log(self.ratio[0]), np.log(self.ratio[1]))
            )
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = self._draws.randint(h - ch + 1)
                j = self._draws.randint(w - cw + 1)
                crop = x[i : i + ch, j : j + cw]
                return _resize(crop, self.size, self.interpolation)
        side = min(h, w)  # fallback: center crop
        i, j = (h - side) // 2, (w - side) // 2
        return _resize(
            x[i : i + side, j : j + side], self.size, self.interpolation
        )


def _resize_nearest(x: np.ndarray, size: int) -> np.ndarray:
    h, w = x.shape[:2]
    ri = (np.arange(size) * h // size).clip(0, h - 1)
    rj = (np.arange(size) * w // size).clip(0, w - 1)
    return x[ri][:, rj]


def _resize_bilinear(
    x: np.ndarray, size: int | tuple[int, int]
) -> np.ndarray:
    """PIL bilinear resize (the torchvision default filter) to
    ``(size, size)`` or ``(h, w)``; uint8 RGB goes through the fast C
    path, everything else per-channel in 'F' mode (rounded, not
    truncated, when cast back to an integer dtype)."""
    from PIL import Image

    th, tw = (size, size) if isinstance(size, int) else size
    if x.dtype == np.uint8 and x.ndim == 3 and x.shape[2] in (3, 4):
        mode = "RGB" if x.shape[2] == 3 else "RGBA"
        im = Image.fromarray(x, mode)
        return np.asarray(im.resize((tw, th), Image.BILINEAR))
    squeeze = x.ndim == 2
    x3 = np.atleast_3d(x)
    chans = [
        np.asarray(
            Image.fromarray(np.asarray(x3[..., c], np.float32), mode="F")
            .resize((tw, th), Image.BILINEAR)
        )
        for c in range(x3.shape[2])
    ]
    out = np.stack(chans, axis=-1)
    if np.issubdtype(x.dtype, np.integer):
        info = np.iinfo(x.dtype)
        out = np.clip(np.rint(out), info.min, info.max)
    out = out.astype(x.dtype)
    return out[..., 0] if squeeze else out


def _resize(x, size, interpolation: str):
    if interpolation == "bilinear":
        return _resize_bilinear(x, size)
    if interpolation == "nearest":
        return _resize_nearest(x, size)
    raise ValueError(
        f"interpolation must be 'bilinear' or 'nearest', got {interpolation!r}"
    )


class Resize:
    """Resize to (size, size); bilinear by default (torchvision's filter,
    needed for top-1 parity on real images), ``interpolation="nearest"``
    for the exact-integer path. NOTE: always square — for torchvision's
    ``Resize(int)`` shorter-side semantics use :class:`ResizeShortestEdge`."""

    def __init__(self, size: int, *, interpolation: str = "bilinear"):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, x):
        return _resize(x, self.size, self.interpolation)


class ResizeShortestEdge:
    """torchvision ``Resize(int)`` semantics: scale the *shorter* side to
    ``size``, preserving aspect ratio (bilinear) — the standard ImageNet
    eval preprocessing (Resize(256) → CenterCrop(224)); a square resize
    there distorts every non-square image and breaks top-1 parity."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, x):
        h, w = x.shape[:2]
        if h <= w:
            th, tw = self.size, max(1, int(round(w * self.size / h)))
        else:
            th, tw = max(1, int(round(h * self.size / w))), self.size
        if (th, tw) == (h, w):
            return x
        return _resize_bilinear(x, (th, tw))


class CenterCrop:
    def __init__(self, size: int):
        self.size = size

    def __call__(self, x):
        h, w = x.shape[:2]
        if h < self.size or w < self.size:
            raise ValueError(
                f"CenterCrop({self.size}) on smaller input {(h, w)}"
            )
        i, j = (h - self.size) // 2, (w - self.size) // 2
        return x[i : i + self.size, j : j + self.size]


class Normalize:
    """(x - mean) / std per channel (expects float input)."""

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, x):
        return (np.asarray(x, np.float32) - self.mean) / self.std


class ToFloat:
    """uint8 [0,255] → float32 [0,1]."""

    def __call__(self, x):
        if x.dtype == np.uint8:
            return x.astype(np.float32) / 255.0
        return np.asarray(x, np.float32)
