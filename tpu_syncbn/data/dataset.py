"""Dataset abstractions.

The reference's data layer is ``torch.utils.data.Dataset`` +
``DataLoader(num_workers=8, pin_memory=True)`` (reference ``README.md:84-91``).
Map-style datasets here follow the same ``__len__``/``__getitem__`` protocol
so user datasets port directly.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable

import numpy as np


class Dataset:
    """Map-style dataset protocol (``__len__`` + ``__getitem__``)."""

    def __len__(self) -> int:
        raise NotImplementedError

    def __getitem__(self, idx: int) -> Any:
        raise NotImplementedError


class ArrayDataset(Dataset):
    """In-memory arrays → (x[i], ..., y[i]) tuples (torch TensorDataset
    analogue)."""

    def __init__(self, *arrays: np.ndarray):
        if not arrays:
            raise ValueError("ArrayDataset needs at least one array")
        n = len(arrays[0])
        if any(len(a) != n for a in arrays):
            raise ValueError("all arrays must share the leading dimension")
        self.arrays = arrays

    def __len__(self):
        return len(self.arrays[0])

    def __getitem__(self, idx):
        out = tuple(a[idx] for a in self.arrays)
        return out if len(out) > 1 else out[0]


class TransformDataset(Dataset):
    """Applies ``transform(sample)`` lazily per item (augmentation hook —
    the work the reference's 8 DataLoader workers do per sample)."""

    def __init__(self, base: Dataset, transform: Callable[[Any], Any]):
        self.base = base
        self.transform = transform

    def __len__(self):
        return len(self.base)

    def __getitem__(self, idx):
        return self.transform(self.base[idx])


class SyntheticImageDataset(Dataset):
    """Deterministic synthetic (image, label) pairs in NHWC — stands in for
    CIFAR/ImageNet in tests and benchmarks (zero-egress environment: no
    downloads). Per-index determinism keeps multi-replica tests exact."""

    def __init__(
        self,
        length: int = 1024,
        shape: tuple[int, int, int] = (32, 32, 3),
        num_classes: int = 10,
        seed: int = 0,
    ):
        self.length = length
        self.shape = shape
        self.num_classes = num_classes
        self.seed = seed

    def __len__(self):
        return self.length

    def __getitem__(self, idx):
        if not 0 <= idx < self.length:
            raise IndexError(idx)
        rng = np.random.RandomState((self.seed * 1_000_003 + idx) % (2**31))
        x = rng.randn(*self.shape).astype(np.float32)
        y = np.int32(rng.randint(self.num_classes))
        return x, y


def load_cifar10(root: str, train: bool = True) -> ArrayDataset | None:
    """Load CIFAR-10 from an on-disk copy of the standard python batches
    (``cifar-10-batches-py``). Returns None when absent — callers fall back
    to :class:`SyntheticImageDataset` (this environment has no egress, so
    the torchvision download path of the reference's typical usage is
    replaced by read-if-present)."""
    base = os.path.join(root, "cifar-10-batches-py")
    if not os.path.isdir(base):
        return None
    names = (
        [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
    )
    xs, ys = [], []
    for name in names:
        path = os.path.join(base, name)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        xs.append(batch[b"data"])
        ys.extend(batch[b"labels"])
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    x = (x.astype(np.float32) / 255.0 - 0.5) / 0.5
    return ArrayDataset(x, np.asarray(ys, np.int32))
