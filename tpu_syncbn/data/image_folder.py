"""Real-image ingestion: JPEG/PNG directory datasets (PIL decode).

The reference's step 5 presumes a working ``Dataset`` of real images fed
to the loader (``/root/reference/README.md:76-91``); this is the
ImageNet-style ``root/<class_name>/<image>.jpg`` reader (torchvision's
``ImageFolder`` layout, which is what `datasets.ImageNet` users actually
point at). Decode happens in the loader workers — PIL releases the GIL
during JPEG decode, so the threaded DataLoader parallelizes it.
"""

from __future__ import annotations

import os
from typing import Callable, Sequence

import numpy as np

from tpu_syncbn.data.dataset import Dataset

IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".webp", ".gif")


def decode_image(path: str) -> np.ndarray:
    """Decode an image file to an RGB uint8 HWC array."""
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"))


class ImageFolderDataset(Dataset):
    """``root/<class_name>/*.jpg`` → ``(image, label)`` samples.

    Classes are the sorted subdirectory names mapped to dense labels
    [0, K) — torchvision ``ImageFolder`` semantics, so an on-disk
    ImageNet/CIFAR tree ports directly. Pass ``class_to_idx`` (e.g. from
    the train split) to pin the mapping for a val split. Samples are
    sorted per class for deterministic indexing; shuffling is the
    sampler's job (``DistributedSampler(shuffle=True)``).
    """

    def __init__(
        self,
        root: str,
        transform: Callable | None = None,
        *,
        extensions: Sequence[str] = IMAGE_EXTENSIONS,
        class_to_idx: dict[str, int] | None = None,
        loader: Callable[[str], np.ndarray] = decode_image,
    ):
        if not os.path.isdir(root):
            raise FileNotFoundError(f"dataset root {root!r} is not a directory")
        self.root = root
        self.transform = transform
        self.loader = loader
        classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
        )
        if class_to_idx is None:
            class_to_idx = {c: i for i, c in enumerate(classes)}
        self.class_to_idx = dict(class_to_idx)
        self.classes = sorted(self.class_to_idx, key=self.class_to_idx.get)
        exts = tuple(e.lower() for e in extensions)
        self.samples: list[tuple[str, int]] = []
        for c in classes:
            if c not in self.class_to_idx:
                continue
            cdir = os.path.join(root, c)
            for name in sorted(os.listdir(cdir)):
                if name.lower().endswith(exts):
                    self.samples.append(
                        (os.path.join(cdir, name), self.class_to_idx[c])
                    )
        if not self.samples:
            raise FileNotFoundError(
                f"no images with extensions {exts} under {root!r}"
            )

    def __len__(self) -> int:
        return len(self.samples)

    def __getitem__(self, idx: int):
        path, label = self.samples[idx]
        image = self.loader(path)
        if self.transform is not None:
            image = self.transform(image)
        return image, np.int32(label)
