"""Batching data loader with background workers and device prefetch.

TPU-native equivalent of the reference's
``DataLoader(dataset, batch_size, num_workers=8, pin_memory=True,
sampler=sampler, drop_last=True)`` (reference ``README.md:84-91``):

* ``num_workers`` background threads fetch+decode samples ahead of the
  training loop (the C++ staging ring buffer in ``native/`` provides the
  zero-copy fast path; this module is the portable engine);
* ``pin_memory``'s role — staging batches so the accelerator copy is
  async — is played by :func:`device_prefetch`, which ``jax.device_put``\\ s
  the next batch(es) onto the chips while the current step runs (double
  buffering), the idiomatic TPU input pipeline (SURVEY §2 native-equivalents
  item 5);
* ``drop_last=True`` at the batch level keeps per-step shapes static — on
  TPU this is not just a convergence nicety but a compile-cache requirement
  (dynamic shapes retrigger XLA compilation).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, Sequence

import jax
import numpy as np

from tpu_syncbn.data.dataset import Dataset
from tpu_syncbn.data.sampler import Sampler, SequentialSampler


def default_collate(samples: Sequence[Any]):
    """Stack a list of samples into batched numpy arrays (mirrors torch's
    default_collate for array/tuple/dict/scalar structures)."""
    first = samples[0]
    if isinstance(first, tuple) and hasattr(first, "_fields"):  # namedtuple
        return type(first)(*(default_collate(list(s)) for s in zip(*samples)))
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate(list(s)) for s in zip(*samples))
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    return np.stack([np.asarray(s) for s in samples])


class DataLoader:
    """Iterates batches of collated samples.

    ``num_workers`` threads run ``dataset[i]`` concurrently (numpy decode
    and IO release the GIL); batch order is deterministic — identical to
    the single-threaded order — because workers fill a slot-addressed
    reorder window, not a free-for-all queue.
    """

    def __init__(
        self,
        dataset: Dataset,
        batch_size: int,
        *,
        sampler: Sampler | None = None,
        num_workers: int = 0,
        drop_last: bool = False,
        collate_fn: Callable = default_collate,
        prefetch_batches: int = 2,
    ):
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if num_workers < 0:
            raise ValueError("num_workers must be >= 0")
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler if sampler is not None else SequentialSampler(len(dataset))
        self.num_workers = num_workers
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self.prefetch_batches = max(1, prefetch_batches)

    def _batches_of_indices(self) -> Iterator[list[int]]:
        batch: list[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self) -> int:
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def __iter__(self):
        if self.num_workers == 0:
            for idxs in self._batches_of_indices():
                yield self.collate_fn([self.dataset[i] for i in idxs])
            return
        yield from self._iter_threaded()

    def _iter_threaded(self):
        """Ordered pipeline: a dispatcher assigns batch slots round-robin;
        each worker collates its own batches; the consumer reassembles in
        slot order so output order matches the sequential loader."""
        n_workers = self.num_workers
        # Per-worker index queues: batch seq goes to worker seq % n_workers,
        # so each worker's output queue is in global-order for its stride
        # and the consumer can reassemble deterministically.
        index_queues = [
            queue.Queue(maxsize=self.prefetch_batches) for _ in range(n_workers)
        ]
        out_queues = [
            queue.Queue(maxsize=self.prefetch_batches) for _ in range(n_workers)
        ]
        stop = threading.Event()
        SENTINEL = None

        def worker(wid: int):
            while True:
                try:
                    item = index_queues[wid].get(timeout=0.05)
                except queue.Empty:
                    if stop.is_set():
                        return
                    continue
                if item is SENTINEL:
                    _put_checking_stop(out_queues[wid], SENTINEL)
                    return
                seq, idxs = item
                try:
                    batch = self.collate_fn([self.dataset[i] for i in idxs])
                except Exception as e:  # propagate to consumer
                    batch = e
                if not _put_checking_stop(out_queues[wid], (seq, batch)):
                    return

        def _put_checking_stop(q, item) -> bool:
            """put() that gives up when the consumer abandoned the
            iterator (stop set), so the dispatcher can never block forever
            on a full queue no one will drain."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        dispatch_error: list[BaseException] = []

        def dispatcher():
            seq = 0
            try:
                for idxs in self._batches_of_indices():
                    if not _put_checking_stop(
                        index_queues[seq % n_workers], (seq, idxs)
                    ):
                        return
                    seq += 1
            except BaseException as e:  # user sampler raised mid-iteration:
                # surface it to the consumer instead of hanging the loop
                dispatch_error.append(e)
                return
            for q in index_queues:
                if not _put_checking_stop(q, SENTINEL):
                    return

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(n_workers)
        ]
        disp = threading.Thread(target=dispatcher, daemon=True)
        for t in threads:
            t.start()
        disp.start()

        try:
            # Batch `seq` was dispatched to worker `seq % n_workers`
            # round-robin (queue.put order == dispatch order per worker),
            # so reading worker queues round-robin restores global order.
            done = [False] * n_workers
            seq = 0
            while not all(done):
                wid = seq % n_workers
                if done[wid]:
                    seq += 1
                    continue
                try:
                    item = out_queues[wid].get(timeout=0.05)
                except queue.Empty:
                    if dispatch_error:
                        raise dispatch_error[0]
                    continue
                if item is SENTINEL:
                    done[wid] = True
                    seq += 1
                    continue
                got_seq, batch = item
                assert got_seq == seq, f"order violation: {got_seq} != {seq}"
                if isinstance(batch, Exception):
                    raise batch
                yield batch
                seq += 1
        finally:
            stop.set()
            # drain so workers blocked on put() can exit (the dispatcher's
            # puts poll `stop` and exit on their own)
            for q in out_queues:
                while not q.empty():
                    q.get_nowait()


def staged_iter(iterator, *, slots: int = 3, slot_mb: int = 64):
    """Route host batches through the native C++ staging ring
    (``native/csrc/staging.cc``) — the pinned-memory staging thread of the
    reference's ``pin_memory=True`` loader (``README.md:88``): a producer
    thread serializes each batch into a reusable 64-byte-aligned slot
    while the consumer devours the previous one, so collation/copy overlap
    the training step without per-batch allocation.

    Batches must be pytrees of numpy arrays (the loader's output). Falls
    back to passing batches through unchanged when the native library is
    unavailable or a batch exceeds ``slot_mb``.
    """
    from tpu_syncbn.runtime import native

    if not native.available():
        yield from iterator
        return

    ring = native.StagingRing(slots, slot_mb << 20)
    SENTINEL = object()
    ERROR = object()
    meta_q: queue.Queue = queue.Queue(maxsize=slots)
    stop = threading.Event()
    # Python-side permit per ring slot: the producer only enters the C++
    # acquire when a slot is guaranteed free, so it can never block inside
    # native code where stop/teardown couldn't reach it (the consumer
    # releases a permit after ring.release).
    free_slots = threading.Semaphore(slots)

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                meta_q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def pack(batch):
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        total = sum(l.nbytes for l in leaves)
        if total > (slot_mb << 20):
            return None  # too big for a slot: bypass
        while not free_slots.acquire(timeout=0.05):
            if stop.is_set():
                return False
        slot, addr = ring.acquire()  # guaranteed non-blocking: permit held
        view = ring.view(addr, total)
        offset = 0
        metas = []
        for l in leaves:
            arr = np.ascontiguousarray(l)
            view[offset : offset + arr.nbytes] = arr.view(np.uint8).ravel()
            metas.append((arr.dtype.str, arr.shape, offset, arr.nbytes))
            offset += arr.nbytes
        ring.commit(slot, total)
        return treedef, metas

    def producer():
        try:
            for batch in iterator:
                packed = pack(batch)
                if packed is False:  # stop requested
                    return
                item = ("bypass", batch) if packed is None else ("slot", packed)
                if not _put(item):
                    return
        except BaseException as e:  # surface at the consumer, don't truncate
            _put((ERROR, e))
            return
        _put((SENTINEL, None))

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            kind, payload = meta_q.get()
            if kind is SENTINEL:
                break
            if kind is ERROR:
                raise payload
            if kind == "bypass":
                yield payload
                continue
            treedef, metas = payload
            slot, addr, size = ring.consume()
            leaves = []
            full = ring.view(addr, size)
            for dtype, shape, offset, nbytes in metas:
                raw = full[offset : offset + nbytes]
                # one copy out of the slot (writable, like every other
                # loader path) so the slot can be recycled immediately
                leaves.append(
                    raw.copy().view(np.dtype(dtype)).reshape(shape)
                )
            ring.release(slot)
            free_slots.release()
            yield jax.tree_util.tree_unflatten(treedef, leaves)
    finally:
        stop.set()
        t.join(timeout=5)  # producer can always observe stop (never blocks
        # in native code), so this join terminates before the ring dies
        ring.close()


def device_prefetch(
    iterator,
    *,
    size: int = 2,
    sharding=None,
    to_device: bool = True,
):
    """Wrap a host-batch iterator with device staging — the pinned-memory +
    async-H2D role of the reference's ``pin_memory=True`` loader thread
    (``README.md:88``; torch's pin thread + ``.to(device)`` at
    ``README.md:57-60``).

    Keeps ``size`` batches in flight: ``jax.device_put`` is async, so the
    next batch's host→HBM DMA overlaps the current step's compute. With
    ``sharding`` (a ``NamedSharding`` over the data axis) the put lands
    each shard directly on its chip — the global-batch feed for the
    data-parallel trainer.
    """
    if size < 1:
        raise ValueError("size must be >= 1")
    multi_host = jax.process_count() > 1

    def put(batch):
        if not to_device:
            return batch
        if sharding is None:
            return jax.tree_util.tree_map(jax.device_put, batch)
        if multi_host:
            # each host feeds its shard of the global batch (the
            # DistributedSampler gave it a disjoint index shard); assemble
            # the logically-global array from per-process local data —
            # jax.device_put can't target non-addressable devices
            return jax.tree_util.tree_map(
                lambda a: jax.make_array_from_process_local_data(sharding, a),
                batch,
            )
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), batch
        )

    buf: list = []
    it = iter(iterator)
    try:
        while len(buf) < size:
            buf.append(put(next(it)))
    except StopIteration:
        pass
    while buf:
        yield buf.pop(0)
        try:
            buf.append(put(next(it)))
        except StopIteration:
            continue
