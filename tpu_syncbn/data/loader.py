"""Batching data loader with background workers and device prefetch.

TPU-native equivalent of the reference's
``DataLoader(dataset, batch_size, num_workers=8, pin_memory=True,
sampler=sampler, drop_last=True)`` (reference ``README.md:84-91``):

* ``num_workers`` background threads fetch+decode samples ahead of the
  training loop (the C++ staging ring buffer in ``native/`` provides the
  zero-copy fast path; this module is the portable engine);
* ``pin_memory``'s role — staging batches so the accelerator copy is
  async — is played by :func:`device_prefetch`, which ``jax.device_put``\\ s
  the next batch(es) onto the chips while the current step runs (double
  buffering), the idiomatic TPU input pipeline (SURVEY §2 native-equivalents
  item 5);
* ``drop_last=True`` at the batch level keeps per-step shapes static — on
  TPU this is not just a convergence nicety but a compile-cache requirement
  (dynamic shapes retrigger XLA compilation).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterator, Sequence

import jax
import numpy as np

from tpu_syncbn.data.dataset import Dataset
from tpu_syncbn.data.sampler import Sampler, SequentialSampler
from tpu_syncbn.obs import stepstats as obs_stepstats
from tpu_syncbn.obs import telemetry


class WorkerError(RuntimeError):
    """A dataset/collate error raised inside a worker process, carrying
    the worker's traceback text."""


class WorkerInfo:
    """What :func:`get_worker_info` returns inside a worker process —
    torch's ``get_worker_info()`` contract. ``dataset`` is the worker's
    OWN (unpickled) copy: mutate/reseed THIS object in a
    ``worker_init_fn``; any transform object captured in the init fn's
    closure would be an unrelated third pickle copy."""

    def __init__(self, id: int, num_workers: int, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info: WorkerInfo | None = None


def get_worker_info() -> WorkerInfo | None:
    """Inside a process worker: this worker's :class:`WorkerInfo`; in the
    main process (or thread workers, which share objects): ``None``."""
    return _worker_info


# Worker wire protocol, shared by thread and process paths:
#   index queue:  ("batch", epoch, seq, idxs) | ("epoch_end", epoch) |
#                 ("stop",)
#   out queue:    ("ok", epoch, seq, batch) | ("err", epoch, seq, err) |
#                 ("epoch_end", epoch) | ("init_err", traceback_text)
# Threads use epoch=0 throughout (workers die with the iterator, so no
# staleness); persistent process workers tag everything with the live
# epoch so batches from an abandoned iteration are dropped, not yielded.


def _persistent_process_worker(
    wid, num_workers, dataset, collate_fn, worker_init_fn, index_q, out_q
):
    """Top-level (spawn-picklable) body for ``worker_type="process"``
    workers. Lives across epochs: ``epoch_end`` is echoed and the loop
    continues; only ``stop`` (or parent exit — daemon) ends it."""
    import traceback

    global _worker_info
    _worker_info = WorkerInfo(id=wid, num_workers=num_workers, dataset=dataset)
    try:
        if worker_init_fn is not None:
            worker_init_fn(wid)
    except Exception:
        out_q.put(("init_err", traceback.format_exc()))
        return
    while True:
        item = index_q.get()
        tag = item[0]
        if tag == "stop":
            return
        if tag == "epoch_end":
            out_q.put(("epoch_end", item[1]))
            continue
        _, epoch, seq, idxs = item
        try:
            out_q.put(("ok", epoch, seq,
                       collate_fn([dataset[i] for i in idxs])))
        except Exception:
            out_q.put(("err", epoch, seq, traceback.format_exc()))


def _bounded_put(q, item, stop: threading.Event) -> bool:
    """put() that gives up when the consumer abandoned the iterator, so
    no producer can block forever on a full queue no one will drain."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.05)
            return True
        except queue.Full:
            continue
    return False


def _queue_depth(out_queues) -> int:
    """Total batches currently buffered across worker out-queues; -1
    where the platform's mp.Queue cannot answer (macOS qsize)."""
    try:
        return sum(q.qsize() for q in out_queues)
    except (NotImplementedError, OSError):
        return -1


def _consume_ordered(out_queues, dispatch_error, *, epoch=0, idle_check=None):
    """Yield batches in dispatch order from per-worker out queues (batch
    ``seq`` was dispatched to worker ``seq % n`` round-robin, so reading
    the queues round-robin restores global order). ``idle_check(wid)``
    may return a final drained item or raise for a dead worker.

    Telemetry (when enabled): per-batch ``loader.fetch_wait_s`` (time the
    consumer spent inside this generator waiting on workers — queue
    starvation shows up here), a ``loader.queue_depth`` gauge sampled at
    each yield (0 with a step-bound consumer means the loader is the
    bottleneck), and a ``loader.batches`` counter."""
    n = len(out_queues)
    done = [False] * n
    seq = 0
    t_resume = time.perf_counter()
    while not all(done):
        wid = seq % n
        if done[wid]:
            seq += 1
            continue
        try:
            item = out_queues[wid].get(timeout=0.05)
        except queue.Empty:
            if dispatch_error:
                raise dispatch_error[0]
            item = idle_check(wid) if idle_check is not None else None
            if item is None:
                continue
        tag = item[0]
        if tag == "init_err":
            raise WorkerError(f"worker {wid} init failed:\n{item[1]}")
        if item[1] != epoch:
            continue  # stale output from an abandoned iteration: drop
        if tag == "epoch_end":
            done[wid] = True
            seq += 1
            continue
        _, _, got_seq, payload = item
        assert got_seq == seq, f"order violation: {got_seq} != {seq}"
        if tag == "err":
            if isinstance(payload, BaseException):
                raise payload  # thread worker: original exception object
            raise WorkerError(f"error in worker {wid}:\n{payload}")
        if telemetry.enabled():
            telemetry.observe(
                "loader.fetch_wait_s", time.perf_counter() - t_resume
            )
            telemetry.set_gauge(
                "loader.queue_depth", _queue_depth(out_queues)
            )
            telemetry.count("loader.batches")
        yield payload
        t_resume = time.perf_counter()
        seq += 1


def _close_pool(pool) -> None:
    """Terminate a process-worker pool. Reached from THREE owners —
    explicit ``close()``, the ``weakref.finalize`` GC/atexit finalizer,
    and interpreter shutdown — so it must be idempotent and must not
    assume queue liveness (a dead worker's queue can already be closed);
    a cleanup path that can crash orphans the very workers it exists to
    reap."""
    if pool.get("closed"):
        return
    pool["closed"] = True
    for q in pool["index_queues"]:
        try:
            q.put_nowait(("stop",))
        except (queue.Full, ValueError, OSError):
            pass  # full, or queue already closed
    for p in pool["procs"]:
        p.join(timeout=0.5)
        if p.is_alive():
            p.terminate()
            p.join(timeout=5)
    for q in (*pool["index_queues"], *pool["out_queues"]):
        try:
            q.cancel_join_thread()
            q.close()
        except (ValueError, OSError):
            pass


def default_collate(samples: Sequence[Any]):
    """Stack a list of samples into batched numpy arrays (mirrors torch's
    default_collate for array/tuple/dict/scalar structures)."""
    first = samples[0]
    if isinstance(first, tuple) and hasattr(first, "_fields"):  # namedtuple
        return type(first)(*(default_collate(list(s)) for s in zip(*samples)))
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate(list(s)) for s in zip(*samples))
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    return np.stack([np.asarray(s) for s in samples])


class DataLoader:
    """Iterates batches of collated samples.

    ``num_workers`` workers run ``dataset[i]`` concurrently; batch order
    is deterministic — identical to the single-threaded order — because
    workers fill a slot-addressed reorder window, not a free-for-all
    queue.

    ``worker_type`` selects the concurrency model. ``"thread"`` (default)
    matches TPU-host reality: PIL's JPEG decode and numpy's transforms
    release the GIL, so threads parallelize the real work without
    process-spawn or pickling overhead. ``"process"`` is the reference's
    literal model (8 worker *processes*, ``README.md:87``) for
    Python-heavy, GIL-bound per-sample work: the dataset and collate_fn
    must be picklable, workers are spawned ONCE per loader and persist
    across epochs (each worker owns a frozen pickle-copy of the dataset
    — parent-side mutations after the first iteration are not seen), and
    ``worker_init_fn(worker_id)`` (torch's ``worker_init_fn``) runs once
    per worker — reseed per-worker augmentation RNGs there via
    ``get_worker_info().dataset``, which is the worker's own copy.
    ``close()`` (or GC) shuts the pool down. Spawn's standard contract
    applies (as for torch's workers on spawn platforms): the training
    script's ``__main__`` must be importable — guard entry with
    ``if __name__ == "__main__":`` and don't drive from a REPL/stdin.
    """

    def __init__(
        self,
        dataset: Dataset,
        batch_size: int,
        *,
        sampler: Sampler | None = None,
        num_workers: int = 0,
        drop_last: bool = False,
        collate_fn: Callable = default_collate,
        prefetch_batches: int = 2,
        worker_type: str = "thread",
        worker_init_fn: Callable[[int], None] | None = None,
    ):
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if num_workers < 0:
            raise ValueError("num_workers must be >= 0")
        if worker_type not in ("thread", "process"):
            raise ValueError(
                f"worker_type must be 'thread' or 'process', got {worker_type!r}"
            )
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler if sampler is not None else SequentialSampler(len(dataset))
        self.num_workers = num_workers
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self.prefetch_batches = max(1, prefetch_batches)
        self.worker_type = worker_type
        self.worker_init_fn = worker_init_fn
        self._pool: dict | None = None
        self._pool_finalizer = None
        self._epoch = 0
        self._iterating = False

    def _batches_of_indices(self) -> Iterator[list[int]]:
        batch: list[int] = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self) -> int:
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def __iter__(self):
        if self.num_workers == 0:
            for idxs in self._batches_of_indices():
                yield self.collate_fn([self.dataset[i] for i in idxs])
            return
        if self.worker_type == "process":
            yield from self._iter_processes()
            return
        yield from self._iter_threaded()

    def _start_dispatcher(self, index_queues, stop, epoch):
        """Feed (epoch, seq)-tagged index batches round-robin, then an
        epoch_end marker per worker. Returns the error box the consumer
        polls (a user sampler raising mid-iteration must surface, not
        hang the loop)."""
        dispatch_error: list[BaseException] = []

        def run():
            seq = 0
            try:
                for idxs in self._batches_of_indices():
                    q = index_queues[seq % len(index_queues)]
                    if not _bounded_put(q, ("batch", epoch, seq, idxs), stop):
                        return
                    seq += 1
            except BaseException as e:
                dispatch_error.append(e)
                return
            for q in index_queues:
                if not _bounded_put(q, ("epoch_end", epoch), stop):
                    return

        threading.Thread(target=run, daemon=True).start()
        return dispatch_error

    # -- process workers ---------------------------------------------------

    def _ensure_pool(self) -> dict:
        """Spawn the persistent worker processes once per loader: spawn
        (fork is unsafe once jax's thread pools exist) re-imports the
        interpreter per worker, so paying it per epoch would stall every
        epoch boundary. Workers live until close()/GC."""
        if self._pool is not None:
            return self._pool
        import multiprocessing as mp
        import weakref

        ctx = mp.get_context("spawn")
        n = self.num_workers
        pool = {
            "index_queues": [
                ctx.Queue(maxsize=self.prefetch_batches) for _ in range(n)
            ],
            "out_queues": [
                ctx.Queue(maxsize=self.prefetch_batches) for _ in range(n)
            ],
        }
        pool["procs"] = [
            ctx.Process(
                target=_persistent_process_worker,
                args=(w, n, self.dataset, self.collate_fn,
                      self.worker_init_fn,
                      pool["index_queues"][w], pool["out_queues"][w]),
                daemon=True,
            )
            for w in range(n)
        ]
        for p in pool["procs"]:
            p.start()
        self._pool = pool
        self._pool_finalizer = weakref.finalize(self, _close_pool, pool)
        return pool

    def close(self) -> None:
        """Shut down persistent process workers. Idempotent: double
        close, close-after-GC-finalize, and close on a thread-mode loader
        (which has no pool) are all safe no-ops. A loader dropped
        *without* close() is reaped by the ``weakref.finalize`` installed
        at pool spawn (which also runs at interpreter exit), so abandoned
        loaders never orphan worker processes."""
        if self._pool is not None:
            if self._pool_finalizer is not None:
                # detach() is None-safe and False when the finalizer
                # already ran (GC beat us): _close_pool is idempotent
                # either way
                self._pool_finalizer.detach()
            _close_pool(self._pool)
            self._pool = None
            self._pool_finalizer = None

    def _iter_processes(self):
        """The reference's worker-process model (``README.md:87``): same
        slot-addressed reorder pipeline as the threaded path, over the
        persistent spawn pool; epoch tags keep outputs of an abandoned
        iteration from leaking into the next."""
        if self._iterating:
            # concurrent iterators would share the pool's queues under
            # different epoch tags and silently starve each other — the
            # thread path supports this (fresh queues per iterator), the
            # persistent pool cannot; fail loudly instead of hanging
            raise RuntimeError(
                "a process-mode DataLoader supports ONE active iterator; "
                "exhaust or abandon the previous iteration first (or use "
                "worker_type='thread' for concurrent iterators)"
            )
        pool = self._ensure_pool()
        self._epoch += 1
        epoch = self._epoch
        self._iterating = True
        stop = threading.Event()
        dispatch_error = self._start_dispatcher(
            pool["index_queues"], stop, epoch
        )

        def idle_check(wid):
            if not pool["procs"][wid].is_alive():
                try:
                    # the worker's final items can still be in the pipe
                    # when the process exits — drain before declaring death
                    return pool["out_queues"][wid].get_nowait()
                except queue.Empty:
                    raise WorkerError(
                        f"worker process {wid} died (exit code "
                        f"{pool['procs'][wid].exitcode}) without reporting"
                    ) from None
            return None

        try:
            yield from _consume_ordered(
                pool["out_queues"], dispatch_error,
                epoch=epoch, idle_check=idle_check,
            )
        finally:
            stop.set()
            self._iterating = False

    # -- thread workers ----------------------------------------------------

    def _iter_threaded(self):
        """Ordered pipeline: a dispatcher assigns batch slots round-robin;
        each worker collates its own batches; the consumer reassembles in
        slot order so output order matches the sequential loader."""
        n_workers = self.num_workers
        index_queues = [
            queue.Queue(maxsize=self.prefetch_batches) for _ in range(n_workers)
        ]
        out_queues = [
            queue.Queue(maxsize=self.prefetch_batches) for _ in range(n_workers)
        ]
        stop = threading.Event()

        def worker(wid: int):
            while True:
                try:
                    item = index_queues[wid].get(timeout=0.05)
                except queue.Empty:
                    if stop.is_set():
                        return
                    continue
                if item[0] == "epoch_end":
                    _bounded_put(out_queues[wid], ("epoch_end", 0), stop)
                    return  # thread workers are per-iteration
                _, _, seq, idxs = item
                try:
                    out = (
                        "ok", 0, seq,
                        self.collate_fn([self.dataset[i] for i in idxs]),
                    )
                except Exception as e:  # same-process: keep the object
                    out = ("err", 0, seq, e)
                if not _bounded_put(out_queues[wid], out, stop):
                    return

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(n_workers)
        ]
        for t in threads:
            t.start()
        dispatch_error = self._start_dispatcher(index_queues, stop, epoch=0)

        try:
            yield from _consume_ordered(out_queues, dispatch_error, epoch=0)
        finally:
            stop.set()
            # drain so workers blocked on put() can exit (the dispatcher's
            # puts poll `stop` and exit on their own)
            for q in out_queues:
                while not q.empty():
                    q.get_nowait()


def staged_iter(iterator, *, slots: int = 3, slot_mb: int = 64):
    """Route host batches through the native C++ staging ring
    (``native/csrc/staging.cc``) — the pinned-memory staging thread of the
    reference's ``pin_memory=True`` loader (``README.md:88``): a producer
    thread serializes each batch into a reusable 64-byte-aligned slot
    while the consumer devours the previous one, so collation/copy overlap
    the training step without per-batch allocation.

    Batches must be pytrees of numpy arrays (the loader's output). Falls
    back to passing batches through unchanged when the native library is
    unavailable or a batch exceeds ``slot_mb``.
    """
    from tpu_syncbn.runtime import native

    if not native.available():
        yield from iterator
        return

    ring = native.StagingRing(slots, slot_mb << 20)
    SENTINEL = object()
    ERROR = object()
    meta_q: queue.Queue = queue.Queue(maxsize=slots)
    stop = threading.Event()
    # Python-side permit per ring slot: the producer only enters the C++
    # acquire when a slot is guaranteed free, so it can never block inside
    # native code where stop/teardown couldn't reach it (the consumer
    # releases a permit after ring.release).
    free_slots = threading.Semaphore(slots)

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                meta_q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def pack(batch):
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        total = sum(l.nbytes for l in leaves)
        if total > (slot_mb << 20):
            return None  # too big for a slot: bypass
        while not free_slots.acquire(timeout=0.05):
            if stop.is_set():
                return False
        slot, addr = ring.acquire()  # guaranteed non-blocking: permit held
        view = ring.view(addr, total)
        offset = 0
        metas = []
        for l in leaves:
            arr = np.ascontiguousarray(l)
            view[offset : offset + arr.nbytes] = arr.view(np.uint8).ravel()
            metas.append((arr.dtype.str, arr.shape, offset, arr.nbytes))
            offset += arr.nbytes
        ring.commit(slot, total)
        return treedef, metas

    def producer():
        try:
            for batch in iterator:
                packed = pack(batch)
                if packed is False:  # stop requested
                    return
                item = ("bypass", batch) if packed is None else ("slot", packed)
                if not _put(item):
                    return
        except BaseException as e:  # surface at the consumer, don't truncate
            _put((ERROR, e))
            return
        _put((SENTINEL, None))

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            # bounded wait + producer-liveness check: the producer's
            # except/sentinel protocol *should* always enqueue a final
            # item, but a thread torn down without one (interpreter
            # shutdown, native crash) must surface as an error here,
            # never as a consumer blocked forever (srclint
            # unbounded_blocking — the PR 9 serving-hardening sweep)
            while True:
                try:
                    kind, payload = meta_q.get(timeout=1.0)
                    break
                except queue.Empty:
                    if not t.is_alive():
                        # the producer can enqueue its final item and
                        # exit between our timeout and this liveness
                        # check — drain once before declaring it dead
                        try:
                            kind, payload = meta_q.get_nowait()
                            break
                        except queue.Empty:
                            raise RuntimeError(
                                "staging producer thread died without "
                                "enqueuing a sentinel or error"
                            ) from None
            if kind is SENTINEL:
                break
            if kind is ERROR:
                raise payload
            if kind == "bypass":
                yield payload
                continue
            treedef, metas = payload
            slot, addr, size = ring.consume()
            leaves = []
            full = ring.view(addr, size)
            for dtype, shape, offset, nbytes in metas:
                raw = full[offset : offset + nbytes]
                # one copy out of the slot (writable, like every other
                # loader path) so the slot can be recycled immediately
                leaves.append(
                    raw.copy().view(np.dtype(dtype)).reshape(shape)
                )
            ring.release(slot)
            free_slots.release()
            yield jax.tree_util.tree_unflatten(treedef, leaves)
    finally:
        stop.set()
        t.join(timeout=5)  # producer can always observe stop (never blocks
        # in native code), so this join terminates before the ring dies
        ring.close()


def device_prefetch(
    iterator,
    *,
    size: int = 2,
    sharding=None,
    to_device: bool = True,
    scan_steps: int = 1,
):
    """Wrap a host-batch iterator with device staging — the pinned-memory +
    async-H2D role of the reference's ``pin_memory=True`` loader thread
    (``README.md:88``; torch's pin thread + ``.to(device)`` at
    ``README.md:57-60``).

    Keeps ``size`` batches in flight: ``jax.device_put`` is async, so the
    next batch's host→HBM DMA overlaps the current step's compute. With
    ``sharding`` (a ``NamedSharding`` over the data axis) the put lands
    each shard directly on its chip — the global-batch feed for the
    data-parallel trainer.

    ``scan_steps=K > 1`` turns the stream into a K-deep device staging
    queue for the fused multi-step driver (docs/PERFORMANCE.md): each
    yielded item stacks K consecutive batches along a new leading axis —
    the layout ``DataParallel.train_steps_batches`` scans over — staged
    with the leading axis unsharded and the per-step batch axis on the
    mesh, while ``size`` chunks stay in flight so the next chunk's h2d
    overlaps the current chunk's K steps. Ownership is donation-safe by
    construction: the host-side stack copies (the source iterator may
    recycle its buffers immediately) and the device chunk is a fresh
    array the trainers never donate. A terminal ``StopIteration`` with a
    non-full staging queue yields one final *partial* chunk (leading
    axis < K — its own compile; feed step counts divisible by K, e.g.
    ``drop_last`` at the chunk level, to avoid it).
    """
    if size < 1:
        raise ValueError("size must be >= 1")
    if scan_steps < 1:
        raise ValueError("scan_steps must be >= 1")
    multi_host = jax.process_count() > 1
    if scan_steps > 1 and sharding is not None:
        from jax.sharding import NamedSharding

        if not isinstance(sharding, NamedSharding):
            raise TypeError(
                "device_prefetch(scan_steps>1) needs a NamedSharding to "
                "derive the K-stacked chunk layout (leading scan axis "
                f"unsharded), got {type(sharding).__name__} — pass the "
                "trainer's batch_sharding"
            )
        # ONE definition of the K-stacked layout rule, shared with
        # DataParallel.scan_batch_sharding — drift here would stage
        # chunks train_steps_batches can't consume without a reshard
        from tpu_syncbn.parallel.layout import SpecLayout
        from tpu_syncbn.parallel.scan_driver import stack_batch_spec

        sharding = SpecLayout.from_mesh(
            sharding.mesh, param_shard_axis=None
        ).sharding(stack_batch_spec(sharding.spec))

    def put(batch):
        if not to_device:
            return batch
        if sharding is None:
            return jax.tree_util.tree_map(jax.device_put, batch)
        if multi_host:
            # each host feeds its shard of the global batch (the
            # DistributedSampler gave it a disjoint index shard); assemble
            # the logically-global array from per-process local data —
            # jax.device_put can't target non-addressable devices
            return jax.tree_util.tree_map(
                lambda a: jax.make_array_from_process_local_data(sharding, a),
                batch,
            )
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sharding), batch
        )

    def staged(it):
        """Fetch + stage the next batch (or K-chunk), instrumented
        (obs.stepstats): ``data_wait`` is the blocking wait on the host
        iterator, ``h2d`` the stack + device_put *dispatch* (the DMA
        itself is async — overlap is the point, so the span measures
        dispatch, not transfer completion). The terminal StopIteration
        fetch is NOT a wait sample (stepstats.timed_fetch) — recording
        it would add one end-of-epoch outlier per epoch."""
        if scan_steps == 1:
            batch = obs_stepstats.timed_fetch(
                it, "data_wait", "loader.data_wait_s"
            )
            with obs_stepstats.timed_span("h2d", "loader.h2d_s"):
                return put(batch)
        # K-slot staging buffer, filled incrementally: each batch is
        # copied into its slot AT FETCH TIME, so the chunk owns its
        # bytes from the moment a batch arrives — a source that recycles
        # one backing buffer across batches (the native staging ring's
        # pattern) cannot retroactively mutate staged slots, and the
        # whole chunk costs one host copy, not two
        slots: list | None = None
        treedef = None
        count = 0
        while count < scan_steps:
            try:
                b = obs_stepstats.timed_fetch(
                    it, "data_wait", "loader.data_wait_s"
                )
            except StopIteration:
                if count == 0:
                    raise  # queue empty: the stream really is over
                break  # partial terminal chunk (leading axis < K)
            leaves, treedef = jax.tree_util.tree_flatten(b)
            if slots is None:
                slots = [
                    np.empty((scan_steps,) + np.shape(l),
                             np.asarray(l).dtype)
                    for l in leaves
                ]
            for s, l in zip(slots, leaves):
                if (np.shape(l) != s.shape[1:]
                        or np.asarray(l).dtype != s.dtype):
                    raise ValueError(
                        f"scan_steps={scan_steps} staging needs static "
                        "batch shapes and dtypes, got "
                        f"{np.shape(l)}/{np.asarray(l).dtype} after "
                        f"{s.shape[1:]}/{s.dtype} — use drop_last=True "
                        "(ragged batches would retrigger XLA compilation "
                        "anyway; a dtype drift would be silently cast)"
                    )
                s[count] = l
            count += 1
        with obs_stepstats.timed_span("h2d", "loader.h2d_s"):
            if telemetry.enabled():
                telemetry.set_gauge("loader.stage_depth", count)
            stacked = jax.tree_util.tree_unflatten(
                treedef,
                [s if count == scan_steps else s[:count] for s in slots],
            )
            return put(stacked)

    buf: list = []
    it = iter(iterator)
    try:
        while len(buf) < size:
            buf.append(staged(it))
    except StopIteration:
        pass
    while buf:
        yield buf.pop(0)
        try:
            buf.append(staged(it))
        except StopIteration:
            continue
