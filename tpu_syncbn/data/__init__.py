"""placeholder"""
