"""Data pipeline: samplers, datasets, loaders, device prefetch (the
reference's L4: DistributedSampler + multi-worker pinned DataLoader,
README.md:74-92)."""

from tpu_syncbn.data.sampler import (
    Sampler,
    SequentialSampler,
    RandomSampler,
    DistributedSampler,
)
from tpu_syncbn.data.dataset import (
    Dataset,
    ArrayDataset,
    TransformDataset,
    SyntheticImageDataset,
    load_cifar10,
)
from tpu_syncbn.data.loader import (
    DataLoader,
    WorkerError,
    WorkerInfo,
    get_worker_info,
    default_collate,
    device_prefetch,
    staged_iter,
)
from tpu_syncbn.data import transforms
from tpu_syncbn.data.detection import (
    SyntheticDetectionDataset,
    CocoDetectionDataset,
    pad_ground_truth,
)
from tpu_syncbn.data.image_folder import ImageFolderDataset, decode_image

__all__ = [
    "WorkerError",
    "WorkerInfo",
    "get_worker_info",
    "ImageFolderDataset",
    "decode_image",
    "SyntheticDetectionDataset",
    "CocoDetectionDataset",
    "pad_ground_truth",
    "staged_iter",
    "transforms",
    "Sampler",
    "SequentialSampler",
    "RandomSampler",
    "DistributedSampler",
    "Dataset",
    "ArrayDataset",
    "TransformDataset",
    "SyntheticImageDataset",
    "load_cifar10",
    "DataLoader",
    "default_collate",
    "device_prefetch",
]
