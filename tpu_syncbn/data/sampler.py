"""Index-space sharding samplers — the reference's ``DistributedSampler``
(reference ``README.md:74-92``; semantics pinned from
``[torch] utils/data/distributed.py:17-157``) rebuilt for per-host sharding.

On TPU the natural shard is per *host* (each host process feeds its local
chips), but the index arithmetic is identical to the reference's per-rank
scheme, and ``num_replicas``/``rank`` remain explicit so tests and the
2-replica capability config can model any world.
"""

from __future__ import annotations

import numpy as np

from tpu_syncbn.runtime import distributed as dist


class Sampler:
    """Iterable of dataset indices (protocol base)."""

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length: int):
        self._length = length

    def __iter__(self):
        return iter(range(self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    """Uniform shuffle, reseeded per epoch via set_epoch (like the
    distributed sampler, so single-replica runs reshuffle identically)."""

    def __init__(self, length: int, seed: int = 0):
        self._length = length
        self._seed = seed
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __iter__(self):
        rng = np.random.RandomState(self._seed + self._epoch)
        return iter(rng.permutation(self._length).tolist())

    def __len__(self):
        return self._length


class DistributedSampler(Sampler):
    """Shard the index space across replicas with the reference's exact
    algorithm (``[torch] utils/data/distributed.py``):

    * seeded per-epoch permutation: ``perm(seed + epoch)`` when ``shuffle``
      (``:110-112``), else ``arange`` (``:113-114``);
    * ``drop_last=False`` → pad by wraparound so every replica gets
      ``ceil(len/world)`` samples (``:116-124``); ``drop_last=True`` →
      truncate to ``floor(len/world)*world`` (``:91-99,127``);
    * strided subsample ``indices[rank::num_replicas]`` (``:134``);
    * ``set_epoch`` required for per-epoch reshuffling (``:146-157``).

    The permutation itself is numpy's (the reference's is torch's CPU
    Philox); the *structure* — disjoint cover, padding, striding, epoch
    seeding — is bit-for-bit the reference algorithm. With ``shuffle=False``
    output is identical to the reference's.
    """

    def __init__(
        self,
        dataset_length: int,
        num_replicas: int | None = None,
        rank: int | None = None,
        *,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        # defaults from the runtime, like torch defaults from the process
        # group ([torch] utils/data/distributed.py:75-82)
        if num_replicas is None:
            num_replicas = dist.process_count()
        if rank is None:
            rank = dist.process_index()
        if not 0 <= rank < num_replicas:
            raise ValueError(
                f"rank {rank} out of range for num_replicas {num_replicas}"
            )
        self.dataset_length = dataset_length
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

        if drop_last and dataset_length % num_replicas != 0:
            self.num_samples = dataset_length // num_replicas  # :91-99
        else:
            self.num_samples = -(-dataset_length // num_replicas)  # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Must be called at each epoch start for reshuffling — same
        contract and same footgun as the reference (``:146-157``)."""
        self.epoch = epoch

    def __iter__(self):
        from tpu_syncbn.runtime import native

        if native.available():
            # native C++ path: bit-identical to the numpy code below
            # (MT19937 parity enforced by tests/test_native.py)
            shard = native.sampler_indices(
                self.dataset_length, self.num_replicas, self.rank,
                self.seed, self.epoch, self.shuffle, self.drop_last,
            )
            if shard is not None:
                assert len(shard) == self.num_samples
                return iter(shard.tolist())

        if self.shuffle:
            # wrap to the 32-bit seed domain so the python and native paths
            # agree for seed+epoch >= 2**32 (numpy would raise otherwise)
            rng = np.random.RandomState((self.seed + self.epoch) % 2**32)  # :110-112
            indices = rng.permutation(self.dataset_length)
        else:
            indices = np.arange(self.dataset_length)  # :113-114

        if not self.drop_last:
            padding = self.total_size - len(indices)  # :116-124 wraparound
            if padding > 0:
                reps = -(-padding // max(len(indices), 1))
                indices = np.concatenate([indices, np.tile(indices, reps)[:padding]])
        else:
            indices = indices[: self.total_size]  # :127
        assert len(indices) == self.total_size

        shard = indices[self.rank : self.total_size : self.num_replicas]  # :134
        assert len(shard) == self.num_samples
        return iter(shard.tolist())

    def __len__(self):
        return self.num_samples
