"""Numerical ops: functional batch-norm kernels (XLA-fused reference path;
Pallas TPU fast path in pallas_bn) and the Pallas flash-attention kernel
(pallas_attention)."""

from tpu_syncbn.ops.batch_norm import (
    get_pallas_mode,
    pallas_mode,
    set_pallas_mode,
    batch_norm_stats,
    moments_from_stats,
    sync_moments,
    batch_norm_elemt,
    update_running_stats,
    batch_norm_train,
    batch_norm_inference,
)

__all__ = [
    "flash_attention",
    "get_pallas_mode",
    "pallas_mode",
    "set_pallas_mode",
    "batch_norm_stats",
    "moments_from_stats",
    "sync_moments",
    "batch_norm_elemt",
    "update_running_stats",
    "batch_norm_train",
    "batch_norm_inference",
]


def __getattr__(name):
    # lazy: importing tpu_syncbn must not pay the Pallas/Mosaic import
    # cost unless the kernel is actually used (the same convention as the
    # function-local pallas_bn imports in batch_norm)
    if name == "flash_attention":
        from tpu_syncbn.ops.pallas_attention import flash_attention

        return flash_attention
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
