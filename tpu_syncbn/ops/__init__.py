"""Numerical ops: functional batch-norm kernels (XLA-fused reference path;
Pallas TPU fast path in pallas_bn)."""

from tpu_syncbn.ops.batch_norm import (
    get_pallas_mode,
    pallas_mode,
    set_pallas_mode,
    batch_norm_stats,
    moments_from_stats,
    sync_moments,
    batch_norm_elemt,
    update_running_stats,
    batch_norm_train,
    batch_norm_inference,
)

__all__ = [
    "get_pallas_mode",
    "pallas_mode",
    "set_pallas_mode",
    "batch_norm_stats",
    "moments_from_stats",
    "sync_moments",
    "batch_norm_elemt",
    "update_running_stats",
    "batch_norm_train",
    "batch_norm_inference",
]
