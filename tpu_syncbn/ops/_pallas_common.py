"""Shared bits for the Pallas kernel modules (pallas_bn,
pallas_attention) — one home so the interpret heuristic and the finite
-inf stand-in cannot silently diverge between kernels.
(``parallel.sequence`` keeps its own ``_NEG_BIG`` copy deliberately:
the parallel layer does not import from ops.)"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# finite stand-in for -inf in masked logits: exp(_NEG_BIG - m) == 0
# without the NaN that a true -inf produces when a whole row is masked
NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)


def interpret() -> bool:
    """Run kernels in interpret mode off-TPU so the CPU test mesh
    exercises the same code path the TPU compiles."""
    return jax.default_backend() != "tpu"


def sds(shape, dtype, like: jax.Array):
    """ShapeDtypeStruct whose varying-axes type matches ``like``: inside
    a ``check_vma=True`` shard_map, pallas_call outputs must declare
    their vma explicitly or lowering fails."""
    from tpu_syncbn import compat

    vma = compat.vma_of(like)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)
