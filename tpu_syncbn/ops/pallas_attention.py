"""Pallas TPU kernel for the attention hot op (flash-style fused softmax).

The reference recipe has no attention (SURVEY §5.7), but this framework
ships sequence parallelism as first-class (``parallel.sequence``), and
the per-device inner loop of every SP scheme is plain causal attention —
the transformer path's hot op, and the natural second Pallas target
after the BN kernels (``ops/pallas_bn.py``).

:func:`flash_attention` computes exact softmax attention in one fused
kernel: the (L, L) score matrix is never materialized — each grid step
holds one (block_q, D) query tile and streams (block_k, D) KV tiles
through VMEM, carrying the online-softmax running (max, denominator,
accumulator) in f32 scratch — the same algorithm
``parallel.sequence._block_attend`` runs at the ring level, pushed down
to the tile level. Under ``causal=True`` the grid itself is compressed:
only the at-or-below-diagonal (qi, ki) tile pairs are enumerated (a 1-D
tile walk mapped through scalar-prefetched index arrays), so tiles
strictly above the diagonal cost neither MXU work NOR VMEM streaming —
the BlockSpec pipeline never touches their DMA (~2x bandwidth cut at
long L vs the rectangular grid); the diagonal tile masks with a 2-D
iota.

Backward is a ``jax.custom_vjp`` with two implementations, both
recomputing P from the saved logsumexp (O(L·block) live memory, never
(L, L)): the default ``"xla"`` path is one ``lax.scan`` over KV blocks;
the opt-in ``"pallas"`` path (``backward="pallas"``) is two fused
kernels in the FlashAttention-2 structure — a dK/dV kernel sweeping
query tiles per KV tile and a dQ kernel sweeping KV tiles per query
tile, f32 VMEM accumulators, causal dead tiles skipping their matmuls.

Like the BN kernels, everything runs under ``interpret=True`` off-TPU
(the CPU suite exercises the real kernel code path), and the kernel is
an *opt-in* backend (``models.transformer``'s ``attn_impl="flash"``)
until a hardware measurement justifies a default — the same
evidence-gating stance as ``ops.batch_norm``'s ``auto``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_syncbn.ops._pallas_common import NEG_BIG as _NEG_BIG
from tpu_syncbn.ops._pallas_common import interpret as _interpret

_BLOCK_Q = 128
_BLOCK_K = 128


from tpu_syncbn.ops._pallas_common import sds as _sds


# -- forward kernel -------------------------------------------------------


def _attend_tile(q_ref, k_ref, v_ref, o_ref, lse_ref,
                 acc_ref, m_ref, l_ref, qi, ki, last_ki, *,
                 scale, causal, block_q, block_k, l_real):
    """One (qi, ki) online-softmax step; ``qi``/``ki`` may be traced
    scalars (compressed causal grid) or program ids (rectangular grid).
    The ki sweep for a fixed (bh, qi) is contiguous in the grid walk, so
    the VMEM scratch carries the running (max, denom, acc) across it."""

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[...] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    q = q_ref[0].astype(jnp.float32) * scale
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (block_q, block_k)
    cols = k_start + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = cols < l_real  # right-pad KV rows are dead
    if causal:
        rows = q_start + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        mask = mask & (rows >= cols)
    s = jnp.where(mask, s, _NEG_BIG)

    m_prev = m_ref[...]  # (block_q, 1)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m_prev - m_new)
    l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[...] = acc_ref[...] * corr + lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[...] = m_new

    @pl.when(ki == last_ki)
    def _finalize():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        # lse rides a (BH, T, 1) array: a 2-D (BH, T) output would put
        # the BH axis in the block's last-two-dims window, where the TPU
        # lowering rejects a block size of 1 (must divide 8 / equal the
        # array dim — observed live in tpu_vma_probe.json round 5)
        lse_ref[0] = m_ref[...] + jnp.log(l)


def _attn_kernel_rect(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *,
                      scale, causal, block_q, block_k, n_k, l_real):
    """Full rectangular grid (BH, n_q, n_k), ki innermost. Non-causal
    always; also the causal fallback when the compressed walk's index
    arrays would be too large for scalar memory — there, above-diagonal
    tiles still stream through VMEM but skip their matmuls."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    if not causal:
        _attend_tile(q_ref, k_ref, v_ref, o_ref, lse_ref,
                     acc_ref, m_ref, l_ref, qi, ki, n_k - 1,
                     scale=scale, causal=False,
                     block_q=block_q, block_k=block_k, l_real=l_real)
        return

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_BIG)
        l_ref[...] = jnp.zeros_like(l_ref)

    # a KV tile strictly right of this query tile's last row touches
    # nothing — skip its matmuls (its DMA still streams in this path)
    live = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(live)
    def _attend():
        _attend_tile(q_ref, k_ref, v_ref, o_ref, lse_ref,
                     acc_ref, m_ref, l_ref, qi, ki, n_k - 1,
                     scale=scale, causal=True,
                     block_q=block_q, block_k=block_k, l_real=l_real)

    @pl.when(ki == n_k - 1)
    def _finalize():
        # _attend_tile's own finalize only fires when the last tile is
        # live, which for a causal row it always is (diagonal end) — but
        # keep the rect path self-sufficient if block ratios change
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(l)


def _attn_kernel_causal(qids_ref, kids_ref, q_ref, k_ref, v_ref,
                        o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                        scale, block_q, block_k, n_k, l_real):
    """Causal: compressed 1-D tile walk (BH, T) over ONLY the live
    (qi, ki) pairs, decoded from the scalar-prefetched index arrays —
    above-diagonal tiles are never visited, so their KV DMA never
    happens. last live ki for a query tile is where the diagonal exits
    its rows (clamped to the KV extent)."""
    t = pl.program_id(1)
    qi = qids_ref[t]
    ki = kids_ref[t]
    last_ki = jnp.minimum(
        n_k - 1, (qi * block_q + block_q - 1) // block_k
    )
    _attend_tile(q_ref, k_ref, v_ref, o_ref, lse_ref,
                 acc_ref, m_ref, l_ref, qi, ki, last_ki,
                 scale=scale, causal=True,
                 block_q=block_q, block_k=block_k, l_real=l_real)


# compressed-walk ceiling: the (qids, kids) int32 pairs live in scalar
# memory (SMEM), which is scarce — past this many tiles fall back to the
# rectangular grid (matmul-skip only). 16384 tiles = 128 KiB of indices
# ~ n_q 180 at equal 128-blocks ~ local L 23k; the SP layer shards
# longer sequences across devices before they reach one kernel.
_MAX_CAUSAL_TILES = 16384


@functools.lru_cache(maxsize=64)
def _causal_tiles(n_q: int, n_k: int, block_q: int, block_k: int):
    """Enumerate live (qi, ki) pairs for the causal lower triangle, qi
    ascending and ki ascending within qi (the scratch-carry contract).
    ~T = n_q(n_q+1)/2 of the rectangular n_q*n_k when blocks match."""
    import numpy as np

    qids, kids = [], []
    for qi in range(n_q):
        k_hi = min(n_k - 1, (qi * block_q + block_q - 1) // block_k)
        for ki in range(k_hi + 1):
            qids.append(qi)
            kids.append(ki)
    return np.asarray(qids, np.int32), np.asarray(kids, np.int32)


@functools.lru_cache(maxsize=64)
def _causal_tiles_kv(n_q: int, n_k: int, block_q: int, block_k: int):
    """The transposed walk for the dK/dV backward kernel: live (ki, qi)
    pairs grouped by ki ascending, qi ascending within ki starting at
    the first query tile that reaches this KV tile's columns
    (qi_lo = (ki*block_k) // block_q) — the scratch carries one KV
    tile's (dk, dv) across its contiguous qi sweep."""
    import numpy as np

    kis, qis = [], []
    for ki in range(n_k):
        for qi in range((ki * block_k) // block_q, n_q):
            kis.append(ki)
            qis.append(qi)
    return np.asarray(kis, np.int32), np.asarray(qis, np.int32)


def _flash_fwd_2d(q, k, v, *, causal, scale, block_q, block_k):
    """(BH, L, D) in → ((BH, L, D) out, (BH, L) logsumexp)."""
    bh, l_real, d = q.shape
    n_q = pl.cdiv(l_real, block_q)
    n_k = pl.cdiv(l_real, block_k)
    pad_q = n_q * block_q - l_real
    pad_k = n_k * block_k - l_real
    qp = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0))) if pad_k else v

    vmem = pltpu.VMEM
    out_shape = [
        _sds((bh, n_q * block_q, d), q.dtype, qp),
        # trailing singleton keeps BH out of the block's last-two-dims
        # window (TPU tiling rule); squeezed before returning
        _sds((bh, n_q * block_q, 1), jnp.float32, qp),
    ]
    scratch_shapes = [
        pltpu.VMEM((block_q, d), jnp.float32),   # acc
        pltpu.VMEM((block_q, 1), jnp.float32),   # running max
        pltpu.VMEM((block_q, 1), jnp.float32),   # running denom
    ]
    if causal:
        # one source of truth for the live-tile set: the gate below must
        # agree exactly with the SMEM index-array size it protects
        qids, kids = _causal_tiles(int(n_q), int(n_k), block_q, block_k)
    if causal and len(qids) <= _MAX_CAUSAL_TILES:
        kernel = functools.partial(
            _attn_kernel_causal, scale=scale,
            block_q=block_q, block_k=block_k, n_k=n_k, l_real=l_real,
        )
        # index maps see (b, t, qids_ref, kids_ref): the tile walk is
        # decoded through the prefetched arrays, so the pipeline only
        # ever streams live KV tiles
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, len(qids)),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda b, t, qids, kids: (b, qids[t], 0),
                             memory_space=vmem),
                pl.BlockSpec((1, block_k, d),
                             lambda b, t, qids, kids: (b, kids[t], 0),
                             memory_space=vmem),
                pl.BlockSpec((1, block_k, d),
                             lambda b, t, qids, kids: (b, kids[t], 0),
                             memory_space=vmem),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda b, t, qids, kids: (b, qids[t], 0),
                             memory_space=vmem),
                pl.BlockSpec((1, block_q, 1),
                             lambda b, t, qids, kids: (b, qids[t], 0),
                             memory_space=vmem),
            ],
            scratch_shapes=scratch_shapes,
        )
        o, lse = pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape,
            interpret=_interpret(),
        )(jnp.asarray(qids), jnp.asarray(kids), qp, kp, vp)
        return o[:, :l_real], lse[:, :l_real, 0]

    kernel = functools.partial(
        _attn_kernel_rect, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, n_k=n_k, l_real=l_real,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=vmem),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=vmem),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=vmem),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=vmem),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                         memory_space=vmem),
        ],
        out_shape=out_shape,
        scratch_shapes=scratch_shapes,
        interpret=_interpret(),
    )(qp, kp, vp)
    return o[:, :l_real], lse[:, :l_real, 0]


# -- backward (XLA, blockwise scan — O(L·block_k) live memory) ------------


def _flash_bwd_2d(res, do, *, causal, scale, block_k):
    q, k, v, o, lse = res  # (BH, L, D)*4, (BH, L)
    bh, l_real, d = q.shape
    n_k = -(-l_real // block_k)
    pad = n_k * block_k - l_real
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
    kb = k.reshape(bh, n_k, block_k, d)
    vb = v.reshape(bh, n_k, block_k, d)

    qf = q.astype(jnp.float32) * scale
    dof = do.astype(jnp.float32)
    # D_i = rowsum(dO ∘ O): the softmax-jacobian diagonal correction
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # (BH, L)
    rows = jnp.arange(l_real)

    def kv_block(carry, blk):
        dq_acc = carry
        k_blk, v_blk, ki = blk  # (BH, block_k, D) ×2, scalar
        cols = ki * block_k + jnp.arange(block_k)
        s = jnp.einsum("bqd,bkd->bqk", qf, k_blk.astype(jnp.float32))
        mask = cols[None, :] < l_real
        if causal:
            mask = mask & (rows[:, None] >= cols[None, :])
        s = jnp.where(mask[None], s, _NEG_BIG)
        p = jnp.exp(s - lse[..., None])  # (BH, L, block_k)
        dv_blk = jnp.einsum("bqk,bqd->bkd", p, dof)
        dp = jnp.einsum("bqd,bkd->bqk", dof, v_blk.astype(jnp.float32))
        ds = p * (dp - delta[..., None])
        dq_acc = dq_acc + jnp.einsum(
            "bqk,bkd->bqd", ds, k_blk.astype(jnp.float32)
        )
        dk_blk = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq_acc, (dk_blk, dv_blk)

    # derive the carry init from a varying operand (qf * 0), not a fresh
    # constant: under check_vma=True a scan carry must keep the same
    # varying type as the body output or lowering fails
    dq0 = qf * 0.0
    dq, (dk_blocks, dv_blocks) = lax.scan(
        kv_block, dq0,
        (kb.transpose(1, 0, 2, 3), vb.transpose(1, 0, 2, 3),
         jnp.arange(n_k)),
    )
    dk = dk_blocks.transpose(1, 0, 2, 3).reshape(bh, n_k * block_k, d)
    dv = dv_blocks.transpose(1, 0, 2, 3).reshape(bh, n_k * block_k, d)
    return (
        (dq * scale).astype(q.dtype),
        dk[:, :l_real].astype(q.dtype),
        dv[:, :l_real].astype(q.dtype),
    )


# -- backward (Pallas, two fused kernels — FlashAttention-2 structure) ----


def _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
              qi, ki, *, scale, causal, block_q, block_k, l_real):
    """Shared per-tile recompute for both backward kernels: returns
    (p, ds, qf, dof) for one (qi, ki) tile, f32, with padded/causal-dead
    entries zeroed. Padded query rows carry a ZERO-padded lse (the fwd
    returns lse only for real rows), so exp(s - lse) is meaningless
    there — dead entries are excluded by mask *selection* on p, which
    keeps every dead contribution exactly zero regardless of what the
    unselected exp evaluates to."""
    qf = q_ref[0].astype(jnp.float32) * scale
    kf = k_ref[0].astype(jnp.float32)
    vf = v_ref[0].astype(jnp.float32)
    dof = do_ref[0].astype(jnp.float32)
    s = lax.dot_general(
        qf, kf, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (block_q, block_k)
    rows = qi * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    cols = ki * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = (rows < l_real) & (cols < l_real)
    if causal:
        mask = mask & (rows >= cols)
    # lse/delta ride (BH, T, 1) arrays (see _flash_fwd_2d's out_shape
    # note), so ref[0] is already the (block_q, 1) broadcast shape
    p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
    dp = lax.dot_general(
        dof, vf, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_ref[0])
    return p, ds, qf, dof


def _bwd_kv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dk_ref, dv_ref, dk_acc, dv_acc, *,
                   scale, causal, block_q, block_k, n_q, l_real):
    """dK/dV: grid (BH, n_k, n_q), qi innermost — the scratch carries
    one KV tile's (dk, dv) across its sweep over query tiles."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # causal: a query tile fully left of this KV tile contributes nothing
    live = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(live)
    def _accum():
        p, ds, qf, dof = _bwd_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
            scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, l_real=l_real,
        )
        dv_acc[...] += lax.dot_general(
            p, dof, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[...] += lax.dot_general(
            ds, qf, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_q_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                  dq_ref, dq_acc, *,
                  scale, causal, block_q, block_k, n_k, l_real):
    """dQ: grid (BH, n_q, n_k), ki innermost — the scratch carries one
    query tile's dq across its sweep over KV tiles."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    live = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _accum():
        _, ds, _, _ = _bwd_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
            scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, l_real=l_real,
        )
        dq_acc[...] += lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_k - 1)
    def _finalize():
        dq_ref[0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _walk_group_bounds(group_ref, t, n_tiles):
    """Group start/end flags for position ``t`` of a compressed tile
    walk, derived from the walk's OWN grouping array (the scalar-
    prefetched kis/qids) rather than re-deriving the diagonal formula —
    one source of truth with the host-side enumeration. A group starts
    where the grouping value changes (or at t=0, which also covers the
    per-batch restart of program_id) and ends where the next value
    differs (or at the final tile)."""
    g = group_ref[t]
    prev = group_ref[jnp.maximum(t - 1, 0)]
    nxt = group_ref[jnp.minimum(t + 1, n_tiles - 1)]
    is_start = (t == 0) | (g != prev)
    is_end = (t == n_tiles - 1) | (g != nxt)
    return is_start, is_end


def _bwd_kv_kernel_c(kis_ref, qis_ref, q_ref, k_ref, v_ref, do_ref,
                     lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                     *, scale, block_q, block_k, n_tiles, l_real):
    """Compressed causal dK/dV: 1-D walk over live (ki, qi) pairs from
    the scalar-prefetched transposed enumeration — dead tiles are never
    visited, so their Q/dO/lse/delta DMA never happens."""
    t = pl.program_id(1)
    ki = kis_ref[t]
    qi = qis_ref[t]
    is_start, is_end = _walk_group_bounds(kis_ref, t, n_tiles)

    @pl.when(is_start)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    p, ds, qf, dof = _bwd_p_ds(
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
        scale=scale, causal=True, block_q=block_q,
        block_k=block_k, l_real=l_real,
    )
    dv_acc[...] += lax.dot_general(
        p, dof, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dk_acc[...] += lax.dot_general(
        ds, qf, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(is_end)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_q_kernel_c(qids_ref, kids_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dq_ref, dq_acc,
                    *, scale, block_q, block_k, n_tiles, l_real):
    """Compressed causal dQ: same walk as the compressed forward."""
    t = pl.program_id(1)
    qi = qids_ref[t]
    ki = kids_ref[t]
    is_start, is_end = _walk_group_bounds(qids_ref, t, n_tiles)

    @pl.when(is_start)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    _, ds, _, _ = _bwd_p_ds(
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
        scale=scale, causal=True, block_q=block_q,
        block_k=block_k, l_real=l_real,
    )
    dq_acc[...] += lax.dot_general(
        ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(is_end)
    def _finalize():
        dq_ref[0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_2d_pallas(res, do, *, causal, scale, block_q, block_k):
    """Fused backward: two pallas_calls (dK/dV then dQ), P recomputed
    tile-by-tile from the saved logsumexp — (L, L) never materialized
    and, unlike the XLA scan path, the per-tile matmuls are explicit
    MXU calls with f32 VMEM accumulators. Under ``causal=True`` both
    kernels use compressed live-tile walks (the forward's DMA-skip
    mechanism; the dK/dV walk is the transposed enumeration), with the
    rectangular matmul-skip grid as the over-cap fallback. Same
    evidence-gating stance as the forward: opt-in
    (``backward="pallas"``) until timed on hardware."""
    q, k, v, o, lse = res
    bh, l_real, d = q.shape
    n_q = pl.cdiv(l_real, block_q)
    n_k = pl.cdiv(l_real, block_k)
    pad_q = n_q * block_q - l_real
    pad_k = n_k * block_k - l_real
    padq = lambda x: jnp.pad(x, ((0, 0), (0, pad_q), (0, 0))) if pad_q else x
    padk = lambda x: jnp.pad(x, ((0, 0), (0, pad_k), (0, 0))) if pad_k else x
    qp, dop = padq(q), padq(do)
    kp, vp = padk(k), padk(v)
    # softmax-jacobian diagonal correction, computed on unpadded rows
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )
    # (BH, T, 1): keep BH out of the block's last-two-dims window (the
    # TPU lowering rejects a 2-D (1, block_q) row block — see forward)
    lsep = padq(lse[..., None])
    deltap = padq(delta[..., None])

    vmem = pltpu.VMEM
    operands = (qp, kp, vp, dop, lsep, deltap)
    kv_out_shape = [
        _sds((bh, n_k * block_k, d), q.dtype, qp),
        _sds((bh, n_k * block_k, d), q.dtype, qp),
    ]
    kv_scratch = [
        pltpu.VMEM((block_k, d), jnp.float32),
        pltpu.VMEM((block_k, d), jnp.float32),
    ]
    compressed = False
    if causal:
        kis, qis = _causal_tiles_kv(int(n_q), int(n_k), block_q, block_k)
        qids, kids = _causal_tiles(int(n_q), int(n_k), block_q, block_k)
        compressed = max(len(kis), len(qids)) <= _MAX_CAUSAL_TILES

    def _walk_specs(q_slot):
        """Operand/row specs for a compressed backward walk whose
        prefetch ref ``q_slot`` (0 or 1) carries the Q-row tile index
        and whose other ref carries the KV-row index. ONE builder for
        both kernels — the two walks differ only in which array means
        what, and a drifted copy would compile but misindex."""
        def q3(b, t, *refs):
            return (b, refs[q_slot][t], 0)

        def kv3(b, t, *refs):
            return (b, refs[1 - q_slot][t], 0)

        in_specs = [
            pl.BlockSpec((1, block_q, d), q3, memory_space=vmem),   # q
            pl.BlockSpec((1, block_k, d), kv3, memory_space=vmem),  # k
            pl.BlockSpec((1, block_k, d), kv3, memory_space=vmem),  # v
            pl.BlockSpec((1, block_q, d), q3, memory_space=vmem),   # do
            pl.BlockSpec((1, block_q, 1), q3, memory_space=vmem),   # lse
            pl.BlockSpec((1, block_q, 1), q3, memory_space=vmem),   # delta
        ]
        return in_specs, q3, kv3

    if compressed:
        in_specs, _, kv3 = _walk_specs(q_slot=1)  # (kis, qis) prefetch
        dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_kv_kernel_c, scale=scale, block_q=block_q,
                block_k=block_k, n_tiles=len(kis), l_real=l_real,
            ),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(bh, len(kis)),
                in_specs=in_specs,
                out_specs=[
                    pl.BlockSpec((1, block_k, d), kv3, memory_space=vmem),
                    pl.BlockSpec((1, block_k, d), kv3, memory_space=vmem),
                ],
                scratch_shapes=kv_scratch,
            ),
            out_shape=kv_out_shape,
            interpret=_interpret(),
        )(jnp.asarray(kis), jnp.asarray(qis), *operands)
    else:
        q_spec_kv = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0),
                                 memory_space=vmem)
        kv_spec_kv = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0),
                                  memory_space=vmem)
        row_spec_kv = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0),
                                   memory_space=vmem)
        dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_kv_kernel, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, n_q=n_q, l_real=l_real,
            ),
            grid=(bh, n_k, n_q),
            in_specs=[q_spec_kv, kv_spec_kv, kv_spec_kv, q_spec_kv,
                      row_spec_kv, row_spec_kv],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0),
                             memory_space=vmem),
                pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0),
                             memory_space=vmem),
            ],
            out_shape=kv_out_shape,
            scratch_shapes=kv_scratch,
            interpret=_interpret(),
        )(*operands)

    if compressed:
        in_specs, q3, _ = _walk_specs(q_slot=0)  # (qids, kids) prefetch
        dq = pl.pallas_call(
            functools.partial(
                _bwd_q_kernel_c, scale=scale, block_q=block_q,
                block_k=block_k, n_tiles=len(qids), l_real=l_real,
            ),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(bh, len(qids)),
                in_specs=in_specs,
                out_specs=pl.BlockSpec((1, block_q, d), q3,
                                       memory_space=vmem),
                scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            ),
            out_shape=_sds((bh, n_q * block_q, d), q.dtype, qp),
            interpret=_interpret(),
        )(jnp.asarray(qids), jnp.asarray(kids), *operands)
    else:
        q_spec_q = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                                memory_space=vmem)
        kv_spec_q = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                                 memory_space=vmem)
        row_spec_q = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                                  memory_space=vmem)
        dq = pl.pallas_call(
            functools.partial(
                _bwd_q_kernel, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, n_k=n_k, l_real=l_real,
            ),
            grid=(bh, n_q, n_k),
            in_specs=[q_spec_q, kv_spec_q, kv_spec_q, q_spec_q,
                      row_spec_q, row_spec_q],
            out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                                   memory_space=vmem),
            out_shape=_sds((bh, n_q * block_q, d), q.dtype, qp),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=_interpret(),
        )(*operands)

    return dq[:, :l_real], dk[:, :l_real], dv[:, :l_real]


# -- public API -----------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_2d(q, k, v, causal, scale, block_q, block_k, backward):
    o, _ = _flash_fwd_2d(q, k, v, causal=causal, scale=scale,
                         block_q=block_q, block_k=block_k)
    return o


def _flash_2d_fwd(q, k, v, causal, scale, block_q, block_k, backward):
    o, lse = _flash_fwd_2d(q, k, v, causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k)
    return o, (q, k, v, o, lse)


def _flash_2d_bwd(causal, scale, block_q, block_k, backward, res, do):
    if backward == "pallas":
        return _flash_bwd_2d_pallas(res, do, causal=causal, scale=scale,
                                    block_q=block_q, block_k=block_k)
    return _flash_bwd_2d(res, do, causal=causal, scale=scale,
                         block_k=block_k)


_flash_2d.defvjp(_flash_2d_fwd, _flash_2d_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = _BLOCK_Q,
    block_k: int = _BLOCK_K,
    backward: str = "xla",
) -> jax.Array:
    """Exact fused softmax attention, ``(B, L, H, D) → (B, L, H, D)``.

    Drop-in for ``parallel.sequence._single_device_attention`` (same
    semantics, tolerances at f32 rounding); differentiable via a
    blockwise custom VJP. ``scale`` defaults to ``D**-0.5``.
    ``backward`` selects the VJP implementation: ``"xla"`` (default —
    blockwise lax.scan) or ``"pallas"`` (two fused kernels, dK/dV then
    dQ; opt-in until timed on hardware, the evidence-gating stance).
    """
    if q.ndim != 4:
        raise ValueError(f"expected (B, L, H, D), got {q.shape}")
    if backward not in ("xla", "pallas"):
        raise ValueError(f"backward must be 'xla' or 'pallas', got "
                         f"{backward!r}")
    # the 2d lowering takes lengths/padding from q and reuses them for
    # k/v (no cross-attention support), and the output reshape assumes
    # v's head_dim == q's — mismatches must fail here with a clear
    # message, not deep in a pallas lowering error
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(
            "flash_attention requires q, k, v of identical (B, L, H, D) "
            f"shape, got q={q.shape}, k={k.shape}, v={v.shape}"
        )
    b, l, h, d = q.shape
    s = float(scale) if scale is not None else d ** -0.5
    to2d = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, l, x.shape[-1])
    o = _flash_2d(to2d(q), to2d(k), to2d(v), causal, s, block_q, block_k,
                  backward)
    return o.reshape(b, h, l, d).transpose(0, 2, 1, 3)
