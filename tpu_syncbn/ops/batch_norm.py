"""Functional batch-normalization ops with exact reference-stack semantics.

These are the TPU-native equivalents of the ATen CUDA kernels the reference's
SyncBN path calls (``batch_norm_stats`` / ``batch_norm_gather_stats_with_counts``
/ ``batch_norm_elemt`` / ``batch_norm_backward_reduce`` /
``batch_norm_backward_elemt``, invoked at
``[torch] nn/modules/_functions.py:39,106,122,145,171``), expressed as pure
functions XLA fuses into the surrounding step. The backward of the
cross-replica ``psum`` is itself a ``psum`` under autodiff — exactly the
reference's backward all_reduce of ``[sum_dy, sum_dy_xmu]``
(``[torch] nn/modules/_functions.py:160-165``) — so no hand-written VJP is
needed for correctness (a fused Pallas fast path lives in
``tpu_syncbn.ops.pallas_bn``).

Semantics pinned to torch 2.13 (SURVEY §7 "hard parts"):

* normalization uses **biased** (1/N) batch variance; the running-var update
  uses the **unbiased** (1/(N-1)) variance
  (``[torch] nn/modules/batchnorm.py:800-812`` and
  ``_functions.py:106-115``);
* ``momentum=None`` means *cumulative average*: the effective update factor
  is ``1/num_batches_tracked`` (``[torch] nn/modules/batchnorm.py:666-667,
  800-812``);
* count-weighted cross-replica aggregation so uneven/empty shards are exact
  (``[torch] nn/modules/_functions.py:50-62``).

Layout: channel-last (NHWC / N...C) by default — the TPU-friendly layout
(lane dimension = channels). A ``channel_axis`` argument covers NCHW.
"""

from __future__ import annotations

import contextlib
import math
import os

import jax
import jax.numpy as jnp

from tpu_syncbn.parallel.collectives import moments_from_stats, reduce_moments

# lazily-resolved 'auto' decision, per process; cleared on every
# set_pallas_mode call (defined before it — set_pallas_mode runs at
# import time for the env-var override below)
_AUTO_PALLAS_CACHE: list = []


def set_pallas_mode(mode: str) -> None:
    """Select the BN kernel backend: 'auto' (on TPU, Pallas if — and only
    if — the committed hardware measurement
    ``benchmarks/artifacts/tpu_syncbn_overhead.json`` shows
    ``pallas_speedup_vs_xla >= 1``; the XLA-fusion path otherwise and on
    every non-TPU backend), 'on' (always Pallas; interpret mode off-TPU),
    'off' (always the XLA-fusion path).

    Read at *trace* time for direct functional calls; the trainers
    (``DataParallel``/``GANTrainer``) additionally snapshot the
    kernel-backend decision (and the matching VMA-checker setting) at
    **construction** — call this BEFORE building a trainer. Steps already
    jit-compiled keep the backend they were traced with.
    """
    global _PALLAS_MODE
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"pallas mode must be auto/on/off, got {mode!r}")
    _PALLAS_MODE = mode
    # every mode change is a full re-decision: an overhead artifact that
    # landed (or a kernel edited) mid-process would otherwise be ignored
    # by a memoized 'auto' until the process restarts
    _AUTO_PALLAS_CACHE.clear()


def get_pallas_mode() -> str:
    """The active BN kernel-backend mode ('auto'/'on'/'off')."""
    return _PALLAS_MODE


@contextlib.contextmanager
def pallas_mode(mode: str):
    """Scoped :func:`set_pallas_mode`: restores the previous mode on exit.
    The same trace-time/construction-time caveats apply — build trainers
    INSIDE the block for the override to take effect."""
    prev = _PALLAS_MODE
    set_pallas_mode(mode)
    try:
        yield
    finally:
        set_pallas_mode(prev)


_PALLAS_MODE = "auto"
_ENV_ALIASES = {
    "1": "on", "true": "on", "yes": "on", "on": "on",
    "0": "off", "false": "off", "no": "off", "off": "off",
    "auto": "auto", "": "auto",
}
_env_mode = os.environ.get("TPU_SYNCBN_PALLAS", "auto").strip().lower()
if _env_mode in _ENV_ALIASES:
    set_pallas_mode(_ENV_ALIASES[_env_mode])
else:
    import warnings

    warnings.warn(
        f"ignoring unrecognized TPU_SYNCBN_PALLAS={_env_mode!r} "
        "(expected on/off/auto or 1/0/true/false); using 'auto'"
    )


def kernel_code_version() -> str:
    """Fingerprint of the BN kernel sources. Hardware evidence (parity
    cases, the overhead measurement gating 'auto') validates a *binary*,
    not a file name — artifacts carry this and are ignored on mismatch."""
    import hashlib

    h = hashlib.sha256()
    here = os.path.dirname(os.path.abspath(__file__))
    # _pallas_common is part of the binary under test (pallas_bn imports
    # its interpret heuristic), so it participates in the fingerprint
    for name in ("pallas_bn.py", "batch_norm.py", "_pallas_common.py"):
        with open(os.path.join(here, name), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _measured_pallas_speedup(path: str | None = None) -> float | None:
    """The committed hardware evidence for the Pallas-vs-XLA decision:
    ``benchmarks/artifacts/tpu_syncbn_overhead.json``'s
    ``pallas_speedup_vs_xla`` (model-level step-time ratio measured on a
    real chip by ``benchmarks/tpu_validation.py``). None when the
    artifact hasn't landed, wasn't TPU-tagged, or measured a different
    kernel version than the one about to trace."""
    import json

    if path is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.path.join(root, "benchmarks", "artifacts",
                            "tpu_syncbn_overhead.json")
    try:
        with open(path) as f:
            parsed = (json.load(f).get("parsed") or {})
    except (OSError, ValueError):
        return None
    if parsed.get("backend") != "tpu":
        return None
    if parsed.get("kernel_code_version") != kernel_code_version():
        return None
    speedup = parsed.get("pallas_speedup_vs_xla")
    return float(speedup) if isinstance(speedup, (int, float)) else None


def _use_pallas() -> bool:
    if _PALLAS_MODE == "on":
        return True
    if _PALLAS_MODE == "off":
        return False
    # 'auto' is evidence-gated: a hand kernel that loses to the XLA
    # fusion it gates out would be a perf regression shipped as the
    # default, so Pallas becomes the TPU default only once the committed
    # hardware measurement shows it >= the XLA path. Until that artifact
    # lands, 'auto' means the XLA-fusion path; Pallas stays one
    # set_pallas_mode("on") away (parity-validated on chip either way).
    if jax.default_backend() != "tpu":
        return False
    if not _AUTO_PALLAS_CACHE:
        speedup = _measured_pallas_speedup()
        _AUTO_PALLAS_CACHE.append(speedup is not None and speedup >= 1.0)
    return _AUTO_PALLAS_CACHE[0]


def _reduction_axes(ndim: int, channel_axis: int) -> tuple[int, ...]:
    ca = channel_axis % ndim
    return tuple(i for i in range(ndim) if i != ca)


def _shape_for_channel(ndim: int, channel_axis: int, c: int) -> list[int]:
    shape = [1] * ndim
    shape[channel_axis % ndim] = c
    return shape


def batch_norm_stats(
    x: jax.Array, *, channel_axis: int = -1
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-channel local partial moments: (sum, sumsq, count).

    Equivalent role to ``torch.batch_norm_stats``
    (``[torch] nn/modules/_functions.py:39``) but returns raw sums rather
    than (mean, invstd): sums compose across replicas with a single psum,
    which is how :func:`sync_moments` aggregates them.

    Accumulates in float32 regardless of input dtype (bf16-safe).
    """
    axes = _reduction_axes(x.ndim, channel_axis)
    xf = x.astype(jnp.float32)
    s = jnp.sum(xf, axis=axes)
    sq = jnp.sum(xf * xf, axis=axes)
    # x.shape is static at trace time: count is a compile-time constant.
    count = jnp.float32(math.prod(x.shape[a] for a in axes))
    return s, sq, count


def sync_moments(
    x: jax.Array,
    *,
    channel_axis: int = -1,
    axis_name: str | None = None,
    group_size: int | tuple | None = None,
    stats_compress: str = "none",
    mask: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-channel (mean, biased var, count) over the batch — cross-replica
    when ``axis_name`` is given.

    This is the fused TPU form of the reference's forward stats exchange:
    local ``batch_norm_stats`` → all_gather of ``[mean, invstd, count]`` →
    ``batch_norm_gather_stats_with_counts``
    (``[torch] nn/modules/_functions.py:39-115``) collapses to local
    (sum, sumsq, count) + one ``psum``.

    ``mask`` (broadcastable to x with channel axis size 1) marks valid
    elements, supporting the uneven/empty-shard contract
    (``_functions.py:50-57``).
    """
    if mask is None:
        s, sq, count = batch_norm_stats(x, channel_axis=channel_axis)
    else:
        axes = _reduction_axes(x.ndim, channel_axis)
        xf = x.astype(jnp.float32)
        mf = jnp.broadcast_to(mask, x.shape).astype(jnp.float32)
        s = jnp.sum(xf * mf, axis=axes)
        sq = jnp.sum(xf * xf * mf, axis=axes)
        count = jnp.sum(mf, axis=axes)  # per-channel (all equal when the
        # mask has channel-axis size 1); reduce_moments handles either form
    if axis_name is not None:
        return reduce_moments(
            s, sq, count, axis_name, group_size=group_size,
            mode=stats_compress,
        )
    mean, var = moments_from_stats(s, sq, count)
    return mean, var, count


def fold_scale_shift(
    mean: jax.Array,
    var: jax.Array,
    weight: jax.Array | None,
    bias: jax.Array | None,
    eps: float,
) -> tuple[jax.Array, jax.Array]:
    """Fold (mean, var, γ, β, eps) into per-channel (scale, shift) so the
    normalize is one FMA per element: ``y = x·scale + shift``. Single home
    for this folding — used by both the XLA and Pallas paths."""
    invstd = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = invstd if weight is None else invstd * weight.astype(jnp.float32)
    shift = -mean.astype(jnp.float32) * scale
    if bias is not None:
        shift = shift + bias.astype(jnp.float32)
    return scale, shift


def batch_norm_elemt(
    x: jax.Array,
    mean: jax.Array,
    var: jax.Array,
    weight: jax.Array | None,
    bias: jax.Array | None,
    eps: float,
    *,
    channel_axis: int = -1,
) -> jax.Array:
    """Elementwise normalize+affine: ``torch.batch_norm_elemt``
    (``[torch] nn/modules/_functions.py:122``). Computes in f32, returns in
    x.dtype; XLA fuses the whole expression into neighbors."""
    shape = _shape_for_channel(x.ndim, channel_axis, mean.shape[0])
    scale, shift = fold_scale_shift(mean, var, weight, bias, eps)
    y = x.astype(jnp.float32) * scale.reshape(shape) + shift.reshape(shape)
    return y.astype(x.dtype)


def update_running_stats(
    running_mean: jax.Array,
    running_var: jax.Array,
    num_batches_tracked: jax.Array,
    batch_mean: jax.Array,
    batch_var: jax.Array,
    count: jax.Array,
    momentum: float | None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Running-stats update with exact torch semantics.

    * increments ``num_batches_tracked`` (``[torch] nn/modules/batchnorm.py:
      800-807``);
    * effective factor = ``momentum``, or ``1/num_batches_tracked`` when
      ``momentum`` is None (cumulative moving average, ``:666-667, 808-812``);
    * running_var absorbs the **unbiased** variance ``var * n/(n-1)``
      (``[torch] nn/modules/_functions.py:106-115`` applies the Bessel
      correction with the *global* count), while normalization uses the
      biased variance. For n<=1 torch would divide by zero; we keep the
      biased value instead of propagating inf into the buffer.
    """
    nbt = num_batches_tracked + 1
    if momentum is None:
        factor = 1.0 / nbt.astype(jnp.float32)
    else:
        factor = jnp.asarray(momentum, jnp.float32)
    unbiased = jnp.where(
        count > 1.0, batch_var * (count / jnp.maximum(count - 1.0, 1.0)), batch_var
    )
    new_mean = (1.0 - factor) * running_mean + factor * batch_mean
    new_var = (1.0 - factor) * running_var + factor * unbiased
    return new_mean, new_var, nbt


def batch_norm_train(
    x: jax.Array,
    running_mean: jax.Array | None,
    running_var: jax.Array | None,
    num_batches_tracked: jax.Array | None,
    weight: jax.Array | None,
    bias: jax.Array | None,
    *,
    momentum: float | None = 0.1,
    eps: float = 1e-5,
    channel_axis: int = -1,
    axis_name: str | None = None,
    group_size: int | tuple | None = None,
    stats_compress: str = "none",
    mask: jax.Array | None = None,
):
    """Full training-mode BN forward (optionally cross-replica synced).
    ``group_size`` scopes the sync to replica subgroups — an int for
    contiguous groups of that size, or an explicit rank partition for
    torch's arbitrary ``process_group`` rank sets (both routed through
    ``parallel.collectives.psum_in_groups``).

    Returns ``(y, (new_running_mean, new_running_var, new_num_batches_tracked))``;
    the stats triple is ``(None, None, None)`` when running stats aren't
    tracked (``track_running_stats=False`` mode, which normalizes by batch
    stats and keeps no buffers).

    With ``axis_name`` set this is SyncBatchNorm: the only cross-replica
    traffic is one fused psum of ``2C+1`` floats — the reference's
    ``all_gather(world×(2C+1))`` + recombine (``_functions.py:41-115``),
    collapsed. Backward under autodiff emits the matching psum of
    ``[sum_dy, sum_dy_xmu]`` exactly as the reference does by hand
    (``_functions.py:160-165``).
    """
    channel_last = channel_axis in (-1, x.ndim - 1)
    if _use_pallas() and channel_last and mask is None \
            and group_size is None and stats_compress == "none":
        # (compressed stats keep the XLA path: the Pallas backward issues
        # its own hand-written psum, which must stay exact)
        # fused Pallas fast path (ops.pallas_bn): one-pass stats kernel,
        # folded normalize, hand-derived backward issuing the reference's
        # exact collectives
        from tpu_syncbn.ops import pallas_bn

        y, mean, var, count = pallas_bn.fused_batch_norm(
            x, weight, bias, eps, axis_name
        )
    else:
        mean, var, count = sync_moments(
            x, channel_axis=channel_axis, axis_name=axis_name,
            group_size=group_size, stats_compress=stats_compress,
            mask=mask,
        )
        y = batch_norm_elemt(
            x, mean, var, weight, bias, eps, channel_axis=channel_axis
        )
    if running_mean is None:
        return y, (None, None, None)
    # Buffers do not participate in autodiff (torch updates them in-place,
    # outside the graph — [torch] nn/modules/_functions.py:106 mutates
    # running stats inside a no-grad kernel).
    mean_s, var_s, count_s = (
        jax.lax.stop_gradient(mean),
        jax.lax.stop_gradient(var),
        jax.lax.stop_gradient(count),
    )
    new_rm, new_rv, nbt = update_running_stats(
        running_mean, running_var, num_batches_tracked, mean_s, var_s, count_s, momentum
    )
    return y, (new_rm, new_rv, nbt)


def batch_norm_inference(
    x: jax.Array,
    running_mean: jax.Array,
    running_var: jax.Array,
    weight: jax.Array | None,
    bias: jax.Array | None,
    *,
    eps: float = 1e-5,
    channel_axis: int = -1,
) -> jax.Array:
    """Eval-mode BN: normalize by running stats, **zero collectives** — the
    reference's non-sync fallback (``[torch] nn/modules/batchnorm.py:863-873``,
    selected when not training per ``:836-842``)."""
    return batch_norm_elemt(
        x, running_mean, running_var, weight, bias, eps, channel_axis=channel_axis
    )
