"""Pallas TPU kernels for the BatchNorm hot ops.

TPU-native equivalents of the ATen CUDA kernels the reference's SyncBN calls
(``batch_norm_stats`` / ``batch_norm_elemt`` / ``batch_norm_backward_reduce``
/ ``batch_norm_backward_elemt``, ``aten/src/ATen/native/cuda/
Normalization.cu``, invoked from ``[torch] nn/modules/_functions.py:39,122,
145,171`` — SURVEY §2 C9, a mandated native-equivalent component).

Three fused single-pass kernels over a channel-last view ``(M, C)`` where
``M = N·H·W``:

* :func:`bn_stats`            — per-channel ``(Σx, Σx²)`` in one read of x.
* :func:`bn_normalize`        — ``y = x·scale + shift`` (scale/shift folded
                                from mean/var/γ/β on the host side of the
                                kernel, so the inner loop is one FMA).
* :func:`bn_backward_reduce`  — per-channel ``(Σdy, Σdy·x̂)`` in one fused
                                read of (dy, x) — these are exactly the two
                                tensors the reference all_reduces in its
                                backward (``_functions.py:160-165``).

All kernels accumulate in float32 VMEM scratch regardless of input dtype
(bf16-safe), tile ``M`` on the sublane axis with channels on the lane axis
(the natural TPU layout), and run under ``interpret=True`` off-TPU so the
CPU test mesh exercises the same code path.

``fused_batch_norm`` wires them into a ``jax.custom_vjp`` whose forward and
backward issue the identical cross-replica psums as the XLA-fusion path in
``ops.batch_norm`` — kernels swap in under the same numerical contract
(golden-tested against both torch and the XLA path).
"""

from __future__ import annotations

import functools


import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_syncbn.parallel.collectives import moments_from_stats

# Max rows per grid step (sublane-aligned); channels ride the 128-wide
# lane axis. 256 is the measured overall best of {128, 256, 512, 1024}
# over the ResNet-50 BN shape set on a v5e chip under the FETCH-SYNCED
# sweep (sum of fused fwd+bwd: 256 -> 28.2 ms, 1024 -> 32.3, 128 ->
# 36.5, 512 -> 44.8; benchmarks/artifacts/tpu_pallas_sweep.json). The
# earlier block-synced sweep ranked 512 first, but that timing was
# voided with the rest of the block-sync artifacts when the tunnel's
# early-readiness bug was caught (tpu_overlap_probe.json); 1024 ranking
# worse than 256 despite being measured last also argues the honest
# ranking is real rather than window drift.
_BLOCK_M = 256

# The fattest kernel (bn_backward_reduce) streams TWO (block, C) operands
# through Pallas's double-buffered pipeline: working set = 2 operands x 2
# buffers x block*C*itemsize. The first on-chip run of the full ResNet-50
# step at block 512, C=2048, f32 hit the TPU's scoped-VMEM ceiling at
# exactly that arithmetic (16.02 MiB vs the 16 MiB limit, watcher log
# 06:57) — a failure the standalone kernel sweep and interpret mode both
# miss. Budget leaves headroom for scratch/semaphores.
_VMEM_BUDGET_BYTES = 14 * 2**20


def _block_m(c: int, itemsize: int) -> int:
    """Largest power-of-two block <= _BLOCK_M whose double-buffered
    two-stream working set fits the scoped-VMEM budget (>= 64 always:
    64*C*16 bytes = 2 MiB even at C=2048 f32)."""
    m = _BLOCK_M
    while m > 64 and 4 * m * c * itemsize > _VMEM_BUDGET_BYTES:
        m //= 2
    return m


from tpu_syncbn.ops._pallas_common import interpret as _interpret


from tpu_syncbn.ops._pallas_common import sds as _sds


def _as_2d(x: jax.Array) -> tuple[jax.Array, int]:
    """Collapse all non-channel axes of a channel-last array into rows."""
    c = x.shape[-1]
    return x.reshape(-1, c), c


def _pad_rows(x2: jax.Array, block: int) -> tuple[jax.Array, int]:
    m = x2.shape[0]
    padded = pl.cdiv(m, block) * block
    if padded != m:
        x2 = jnp.pad(x2, ((0, padded - m), (0, 0)))
    return x2, m


# -- stats kernel ---------------------------------------------------------


def _stats_kernel(x_ref, sum_ref, sumsq_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xf = x_ref[...].astype(jnp.float32)
    acc_ref[0, :] += jnp.sum(xf, axis=0)
    acc_ref[1, :] += jnp.sum(xf * xf, axis=0)

    @pl.when(i == pl.num_programs(0) - 1)
    def _done():
        # (1, C) outputs: 2-D lane-aligned layout per the TPU tiling rules
        sum_ref[0, :] = acc_ref[0, :]
        sumsq_ref[0, :] = acc_ref[1, :]


def bn_stats(x: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused per-channel (sum, sumsq, count) — one pass over x.

    Same contract as ``ops.batch_norm.batch_norm_stats`` (the XLA path);
    the reference's ``batch_norm_stats`` CUDA kernel returns (mean, invstd)
    but raw sums compose across replicas with a single psum (SURVEY §7).
    """
    x2, c = _as_2d(x)
    block = _block_m(c, x.dtype.itemsize)
    x2, m = _pad_rows(x2, block)  # zero rows contribute 0 to both sums
    s, sq = _stats_2d(x2, c, block)
    return s, sq, jnp.float32(m)


def _stats_2d(x2: jax.Array, c: int, block: int) -> tuple[jax.Array, jax.Array]:
    """Stats kernel over an (M', C) view already padded to ``block``."""
    grid = (x2.shape[0] // block,)
    s, sq = pl.pallas_call(
        _stats_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, c), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=[
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((1, c), jnp.float32, x2),
            _sds((1, c), jnp.float32, x2),
        ],
        scratch_shapes=[pltpu.VMEM((2, c), jnp.float32)],
        interpret=_interpret(),
    )(x2)
    return s[0], sq[0]


# -- normalize kernel -----------------------------------------------------


def _normalize_kernel(x_ref, scale_ref, shift_ref, y_ref):
    xf = x_ref[...].astype(jnp.float32)
    y = xf * scale_ref[0, :] + shift_ref[0, :]
    y_ref[...] = y.astype(y_ref.dtype)


def bn_normalize(
    x: jax.Array,
    mean: jax.Array,
    var: jax.Array,
    weight: jax.Array | None,
    bias: jax.Array | None,
    eps: float,
) -> jax.Array:
    """Fused elementwise normalize+affine (``batch_norm_elemt``,
    ``[torch] nn/modules/_functions.py:122``): scale/shift are folded to
    one FMA per element (shared folding in ops.batch_norm)."""
    from tpu_syncbn.ops.batch_norm import fold_scale_shift

    scale, shift = fold_scale_shift(mean, var, weight, bias, eps)
    x2, c = _as_2d(x)
    block = _block_m(c, x.dtype.itemsize)
    x2p, m = _pad_rows(x2, block)
    y = _normalize_2d(x2p, scale, shift, c, x.dtype, block)
    return y[:m].reshape(x.shape)


def _normalize_2d(x2p, scale, shift, c, out_dtype, block):
    """Normalize kernel over an (M', C) view already padded to ``block``."""
    grid = (x2p.shape[0] // block,)
    return pl.pallas_call(
        _normalize_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, c), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (block, c), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=_sds(x2p.shape, out_dtype, x2p),
        interpret=_interpret(),
    )(x2p, scale[None], shift[None])


# -- backward reduce kernel ----------------------------------------------


def _bwd_reduce_kernel(dy_ref, x_ref, mean_ref, invstd_ref, sdy_ref, sdyx_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    dyf = dy_ref[...].astype(jnp.float32)
    xf = x_ref[...].astype(jnp.float32)
    xhat = (xf - mean_ref[0, :]) * invstd_ref[0, :]
    acc_ref[0, :] += jnp.sum(dyf, axis=0)
    acc_ref[1, :] += jnp.sum(dyf * xhat, axis=0)

    @pl.when(i == pl.num_programs(0) - 1)
    def _done():
        sdy_ref[0, :] = acc_ref[0, :]
        sdyx_ref[0, :] = acc_ref[1, :]


def bn_backward_reduce(
    dy: jax.Array, x: jax.Array, mean: jax.Array, invstd: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Fused per-channel (Σdy, Σdy·x̂) — the ``batch_norm_backward_reduce``
    kernel (``[torch] nn/modules/_functions.py:145-154``); Σdy·x̂ relates to
    torch's ``sum_dy_xmu`` by the invstd factor. Zero-padded rows contribute
    dy=0, so the sums are exact."""
    dy2, c = _as_2d(dy)
    x2, _ = _as_2d(x)
    block = _block_m(c, max(dy.dtype.itemsize, x.dtype.itemsize))
    dy2, m = _pad_rows(dy2, block)
    x2, _ = _pad_rows(x2, block)
    grid = (dy2.shape[0] // block,)
    sdy, sdyx = pl.pallas_call(
        _bwd_reduce_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, c), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block, c), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _sds((1, c), jnp.float32, dy2),
            _sds((1, c), jnp.float32, dy2),
        ],
        scratch_shapes=[pltpu.VMEM((2, c), jnp.float32)],
        interpret=_interpret(),
    )(dy2, x2, mean[None], invstd[None])
    return sdy[0], sdyx[0]


# -- fused custom-vjp batch norm -----------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_batch_norm(x, weight, bias, eps: float, axis_name: str | None):
    """Training-mode BN forward via Pallas kernels, with the hand-derived
    backward of the reference (``[torch] nn/modules/_functions.py:128-180``):
    forward psums (Σx, Σx², n); backward psums (Σdy, Σdy·x̂) — byte-for-byte
    the reference's two collectives, fused kernels in between.

    Returns ``(y, mean, var, count)`` (stats needed for the running-stat
    update, which stays outside the differentiable path)."""
    y, mean, var, count, _ = _fbn_fwd_impl(x, weight, bias, eps, axis_name)
    return y, mean, var, count


def _fbn_fwd_impl(x, weight, bias, eps, axis_name):
    from tpu_syncbn.ops.batch_norm import fold_scale_shift

    # pad the (M, C) view ONCE; both kernels share it
    x2, c = _as_2d(x)
    block = _block_m(c, x.dtype.itemsize)
    x2p, m = _pad_rows(x2, block)
    s, sq = _stats_2d(x2p, c, block)
    count = jnp.float32(m)
    if axis_name is not None:
        s, sq, count = jax.lax.psum((s, sq, count), axis_name)
    mean, var = moments_from_stats(s, sq, count)
    scale, shift = fold_scale_shift(mean, var, weight, bias, eps)
    y = _normalize_2d(x2p, scale, shift, c, x.dtype, block)[:m].reshape(x.shape)
    invstd = jax.lax.rsqrt(var + eps)
    return y, mean, var, count, invstd


def _unwrap_primal(p):
    from jax.custom_derivatives import CustomVJPPrimal

    return p.value if isinstance(p, CustomVJPPrimal) else p


def _fbn_fwd(x, weight, bias, eps, axis_name):
    # symbolic_zeros=True wraps each diff argument in CustomVJPPrimal
    x, weight, bias = map(_unwrap_primal, (x, weight, bias))
    y, mean, var, count, invstd = _fbn_fwd_impl(x, weight, bias, eps, axis_name)
    return (y, mean, var, count), (x, weight, bias, mean, invstd, count)


def _fbn_bwd(eps, axis_name, res, cts):
    from jax.custom_derivatives import SymbolicZero

    x, weight, bias, mean, invstd, count = res
    dy, *stat_cts = cts
    # The mean/var/count outputs feed the (no-grad) running-buffer update
    # only, as in the reference where that update happens inside a no-grad
    # kernel; this VJP defines no gradient for them. symbolic_zeros lets us
    # verify the caller isn't differentiating through them — silently
    # returning zero for a requested gradient would be a wrong answer.
    for name, ct in zip(("mean", "var", "count"), stat_cts):
        if not isinstance(ct, SymbolicZero):
            raise ValueError(
                f"fused_batch_norm defines no gradient for its '{name}' "
                "statistic output (stats feed the no-grad running-buffer "
                "update only); apply jax.lax.stop_gradient to the stats or "
                "differentiate through y alone"
            )
    if isinstance(dy, SymbolicZero):  # only stats were used downstream
        dy = jnp.zeros(dy.shape, dy.dtype)

    sum_dy, sum_dy_xhat = bn_backward_reduce(dy, x, mean, invstd)

    # grad wrt weight/bias use the LOCAL per-replica sums: the reference
    # computes them from the local backward_reduce (_functions.py:145-158)
    # and lets DDP's gradient all-reduce aggregate across replicas — here
    # the outer grad aggregation (shard_map transpose / trainer pmean)
    # plays that role. Using the psum'd sums would double-count by world.
    grad_weight = None if weight is None else sum_dy_xhat
    grad_bias = None if bias is None else sum_dy

    if axis_name is not None:
        # the reference's backward all_reduce(SUM) of [sum_dy, sum_dy_xmu]
        # (_functions.py:160-165) — feeds dx only
        sum_dy, sum_dy_xhat = jax.lax.psum((sum_dy, sum_dy_xhat), axis_name)

    # batch_norm_backward_elemt: dx = (dy - Σdy/n - x̂·Σdy·x̂/n)·invstd·γ
    c = x.shape[-1]
    w = jnp.ones((c,), jnp.float32) if weight is None else weight.astype(jnp.float32)
    mean_dy = sum_dy / count
    mean_dy_xhat = sum_dy_xhat / count

    def dx_fn(xv, dyv):
        xhat = (xv.astype(jnp.float32) - mean) * invstd
        dxv = (
            (dyv.astype(jnp.float32) - mean_dy - xhat * mean_dy_xhat)
            * invstd
            * w
        )
        return dxv.astype(xv.dtype)

    dx = dx_fn(x, dy)
    gw = None if weight is None else grad_weight.astype(weight.dtype)
    gb = None if bias is None else grad_bias.astype(bias.dtype)
    return dx, gw, gb


fused_batch_norm.defvjp(_fbn_fwd, _fbn_bwd, symbolic_zeros=True)
