"""Version-compat shims: gate the few current-jax/flax APIs this codebase
uses so the package *imports and degrades* instead of crashing on an older
baked toolchain (observed container: jax 0.4.x / flax 0.10, where
``jax.shard_map`` lives in ``jax.experimental.shard_map`` with a
``check_rep`` flag instead of the VMA type system's ``check_vma``, and the
``nnx.to_pure_dict`` module functions are still ``State`` methods).

Robustness contract (docs/RESILIENCE.md): a missing optional API selects a
documented fallback path once, at import; it never raises mid-step. The
fallbacks are semantic no-ops for correctness-relevant behavior:

* ``shard_map(check_vma=...)`` → legacy shard_map with ``check_rep=False``.
  The VMA checker is an extra *validator*; legacy shard_map without
  ``lax.pvary`` has no implicit varying-cast/psum insertion, so gradients
  stay replica-local and the trainer's explicit ``pmean`` remains the one
  aggregation (the round-1 "8x off" hazard does not exist on this path).
* ``HAS_VMA=False`` additionally makes ``pcast_varying`` the identity —
  there is no VMA type to cast.
* ``nnx_merge(..., copy=True)`` falls back to plain ``nnx.merge`` (flax
  versions without the kwarg construct fresh Variables already).
"""

from __future__ import annotations

from typing import Any

import jax

#: True when this jax has the VMA (varying-manual-axes) type system —
#: ``lax.pvary``/``lax.pcast`` and shard_map's ``check_vma``.
HAS_VMA: bool = hasattr(jax.lax, "pvary")

_NATIVE_SHARD_MAP: bool = hasattr(jax, "shard_map")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the ``check_vma`` kwarg, on any supported
    jax. On pre-VMA jax the legacy ``jax.experimental.shard_map`` runs
    with ``check_rep=False``: ``check_rep`` is a different (replication)
    checker that several of our step programs legitimately fail — e.g.
    per-replica buffer storage — and the VMA-cast machinery that keeps
    the modern checker satisfied is an identity here (``HAS_VMA``)."""
    if _NATIVE_SHARD_MAP:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as legacy_shard_map

    return legacy_shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        # check_rep=False unconditionally: the legacy checker neither
        # fixes the legacy transpose limitation for replicated args
        # (tested) nor accepts all our step programs; the modern
        # checker's guarantees simply don't exist on this toolchain
        check_rep=False,
    )


def axis_size(axis_name):
    """``lax.axis_size`` where available; otherwise the classic
    ``psum(1, axis)`` identity (folded to a static constant at trace
    time — no runtime collective). A tuple/list of axis names yields the
    product of the per-axis sizes — the total replica count of a composed
    layout like ``('data', 'fsdp')`` — and raises the same
    NameError/KeyError as the single-axis form when *any* member axis is
    out of scope (callers probing scope rely on that)."""
    if isinstance(axis_name, (tuple, list)):
        size = 1
        for a in axis_name:
            size = size * axis_size(a)
        return size
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def vma_of(x) -> frozenset:
    """The VMA (varying axes) set of a traced value; empty on pre-VMA
    jax, where every value is effectively unvarying."""
    if not hasattr(jax, "typeof"):
        return frozenset()
    return getattr(jax.typeof(x), "vma", frozenset()) or frozenset()


def nnx_list(items):
    """``nnx.List`` where flax has it; a plain Python list otherwise
    (older nnx registers plain lists as graph nodes, so child modules
    and their params stay visible to split/merge either way)."""
    from flax import nnx

    if hasattr(nnx, "List"):
        return nnx.List(items)
    return list(items)


def nnx_dict(mapping):
    """``nnx.Dict`` where flax has it; a plain dict otherwise (older nnx
    registers plain dicts as graph nodes)."""
    from flax import nnx

    if hasattr(nnx, "Dict"):
        return nnx.Dict(mapping)
    return dict(mapping)


def nnx_data(value):
    """``nnx.data`` (explicit data-attribute annotation on current flax)
    — identity on older flax, which treats container attributes as graph
    data without annotation."""
    from flax import nnx

    if hasattr(nnx, "data"):
        return nnx.data(value)
    return value


_MERGE_HAS_COPY: bool | None = None


def nnx_merge(graphdef, *states, copy: bool = True):
    """``nnx.merge`` forwarding ``copy=`` only where flax supports it
    (the kwarg exists to force fresh trace-local Variables on flax
    versions whose merge aliases the originals; older merges already
    materialize fresh Variables). Support is probed from the signature
    once — NOT by catching TypeError, which would silently retry a merge
    whose *real* failure was elsewhere and reintroduce the aliasing bug
    ``copy=True`` exists to prevent."""
    import inspect

    from flax import nnx

    global _MERGE_HAS_COPY
    if _MERGE_HAS_COPY is None:
        try:
            params = inspect.signature(nnx.merge).parameters
            _MERGE_HAS_COPY = "copy" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
        except (TypeError, ValueError):
            _MERGE_HAS_COPY = True  # unsignaturable: assume modern flax
    if _MERGE_HAS_COPY:
        return nnx.merge(graphdef, *states, copy=copy)
    return nnx.merge(graphdef, *states)


def nnx_to_pure_dict(state) -> Any:
    """``nnx.to_pure_dict`` (module function on current flax, ``State``
    method on older)."""
    from flax import nnx

    if hasattr(nnx, "to_pure_dict"):
        return nnx.to_pure_dict(state)
    return state.to_pure_dict()


def nnx_replace_by_pure_dict(state, pure) -> None:
    """``nnx.replace_by_pure_dict`` (module function on current flax,
    ``State`` method on older). Mutates ``state`` in place."""
    from flax import nnx

    if hasattr(nnx, "replace_by_pure_dict"):
        nnx.replace_by_pure_dict(state, pure)
    else:
        state.replace_by_pure_dict(pure)
