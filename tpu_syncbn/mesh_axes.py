"""Canonical mesh-axis names — the ONE module allowed to spell them as
string literals.

Every parallelism strategy names its mesh axis here and imports the
constant; the ``hardcoded_mesh_axis`` srclint rule
(:mod:`tpu_syncbn.audit.srclint`) fails the build on a string literal
axis name anywhere else in the package. Why this is worth a lint: the
ROADMAP item-1 unification folds DP × FSDP × TP onto one multi-axis
mesh, and a layout object can only rename/compose axes mechanically if
no call site has its own private ``"data"`` — a stray literal is
exactly the kind of silent coupling that turns a mesh refactor into a
week of grepping.

This module must stay import-free (stdlib only, no jax): it is imported
by :mod:`tpu_syncbn.runtime.distributed` while the package ``__init__``
is still executing, so any dependency here would recreate the circular
import it exists to avoid.
"""

#: Data-parallel axis: the reference recipe's "process group" of N
#: single-GPU workers as one named axis spanning every chip.
DATA_AXIS = "data"

#: Tensor (model) parallel axis — Megatron-style sharded linears
#: (:mod:`tpu_syncbn.parallel.tensor`).
MODEL_AXIS = "model"

#: Fully-sharded-data-parallel axis, reserved for the ROADMAP item-1
#: ``P(('data','fsdp'))`` composed layout (ZeRO today shards along
#: :data:`DATA_AXIS`; the SpecLayout refactor gives the shard dimension
#: its own name so DP and FSDP can coexist on a 2-D mesh).
FSDP_AXIS = "fsdp"

#: Pipeline-parallel stage axis (:mod:`tpu_syncbn.parallel.pipeline`).
PIPE_AXIS = "pipe"

#: Expert-parallel axis (:mod:`tpu_syncbn.parallel.expert`).
EXPERT_AXIS = "expert"

#: Sequence/context-parallel axis (:mod:`tpu_syncbn.parallel.sequence`).
SEQ_AXIS = "seq"

#: Every axis name the framework may put on a mesh, in layout order
#: (data-like outermost). The item-1 SpecLayout will validate its mesh
#: axes against this tuple.
ALL_AXES = (DATA_AXIS, FSDP_AXIS, MODEL_AXIS, PIPE_AXIS, EXPERT_AXIS,
            SEQ_AXIS)
