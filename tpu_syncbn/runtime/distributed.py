"""Distributed runtime: initialization, device mesh construction, process
identity, and rank-0 conventions.

This module is the TPU-native replacement for the reference recipe's entire
process/rendezvous stack (reference ``README.md:22-36``):

* ``argparse --local_rank`` (``README.md:11-19``) — not needed. TPU training
  is single-program multi-device: one Python process per *host*, all chips
  driven from it. Process identity comes from the TPU slice metadata via
  :func:`process_index`, not from a launcher-injected CLI argument.
* ``torch.cuda.set_device(local_rank)`` (``README.md:27``) — not needed.
  Each host process owns its local chips implicitly from slice topology.
* ``init_process_group('nccl', init_method='env://', world_size, rank)``
  (``README.md:29-35``) — replaced by :func:`initialize`, which (on
  multi-host) calls ``jax.distributed.initialize`` to join the slice's
  coordination service, then builds a :class:`jax.sharding.Mesh` over all
  chips. Collectives become XLA AllReduce/AllGather HLOs over ICI/DCN
  instead of runtime-issued NCCL calls.
* rank-0 "master" logging convention (``README.md:9``) — :func:`is_master` /
  :func:`master_print`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import sys
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

_loggers: dict[str, logging.Logger] = {}
_initialized: bool = False
_jax_distributed_active: bool = False

#: Name of the data-parallel mesh axis used throughout the framework. The
#: reference's "process group" of N single-GPU processes (README.md:5)
#: becomes this one named axis spanning every chip in the slice.
#: Canonically defined in :mod:`tpu_syncbn.mesh_axes` (the one module
#: allowed to spell axis names as literals — srclint
#: ``hardcoded_mesh_axis``); re-exported here for the historical import
#: path every trainer uses.
from tpu_syncbn.mesh_axes import DATA_AXIS  # noqa: E402


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    """Explicit multi-host wiring, mirroring the env contract the reference's
    launcher sets (``MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE``; reference
    ``README.md:32-35`` reads them via ``init_method='env://'``).

    All fields default to ``None`` meaning "autodetect from the environment"
    — on a real TPU slice, ``jax.distributed.initialize`` discovers
    everything from slice metadata and none of this is needed.
    """

    coordinator_address: str | None = None  # MASTER_ADDR:MASTER_PORT analogue
    num_processes: int | None = None        # WORLD_SIZE analogue (hosts, not chips)
    process_id: int | None = None           # RANK analogue

    @staticmethod
    def from_env() -> "DistributedConfig":
        """Read the reference-compatible env contract if present.

        Honors both our names (``TPU_SYNCBN_COORDINATOR`` etc.) and the
        reference's torchrun names (``MASTER_ADDR``/``MASTER_PORT``/``RANK``/
        ``WORLD_SIZE``; documented in the reference at ``README.md:32-35``)
        so scripts written against the recipe's env contract keep working.
        """
        addr = os.environ.get("TPU_SYNCBN_COORDINATOR")
        if addr is None and "MASTER_ADDR" in os.environ:
            port = os.environ.get("MASTER_PORT", "12355")
            addr = f"{os.environ['MASTER_ADDR']}:{port}"
        nproc = os.environ.get("TPU_SYNCBN_NUM_PROCESSES", os.environ.get("WORLD_SIZE"))
        pid = os.environ.get("TPU_SYNCBN_PROCESS_ID", os.environ.get("RANK"))
        return DistributedConfig(
            coordinator_address=addr,
            num_processes=int(nproc) if nproc is not None else None,
            process_id=int(pid) if pid is not None else None,
        )


def initialize(
    config: DistributedConfig | None = None,
    *,
    rendezvous_attempts: int | None = None,
    rendezvous_timeout_s: float | None = None,
    rendezvous_backoff_s: float | None = None,
) -> None:
    """Join the distributed job. One call replaces the reference's step 1+2
    (``--local_rank`` parse, ``cuda.set_device``, ``init_process_group``;
    ``README.md:11-36``).

    Single-host (including the 1-chip and forced-host-device test cases):
    a no-op beyond marking the runtime initialized — JAX already sees all
    local devices.

    Multi-host: calls ``jax.distributed.initialize``, which performs the
    rendezvous the reference does through ``env://`` + TCPStore
    (``[torch] distributed/distributed_c10d.py:1889``) but against the TPU
    coordination service. On a Cloud TPU slice all arguments are discovered
    from slice metadata and ``config`` may be ``None``.

    The rendezvous is retried with exponential backoff and deterministic
    per-host jitter (docs/RESILIENCE.md): coordinator DNS that isn't up
    yet, a coordinator restarting after preemption, or a slow-starting
    peer should cost a retry, not the job. Knobs (argument > env >
    default): ``rendezvous_attempts`` / ``TPU_SYNCBN_RENDEZVOUS_ATTEMPTS``
    (default 3), ``rendezvous_timeout_s`` /
    ``TPU_SYNCBN_RENDEZVOUS_TIMEOUT_S`` (per-attempt timeout handed to
    ``jax.distributed.initialize`` where supported; jax's default
    otherwise), ``rendezvous_backoff_s`` /
    ``TPU_SYNCBN_RENDEZVOUS_BACKOFF_S`` (base backoff, default 1.0).
    """
    global _initialized, _jax_distributed_active
    if _initialized:
        return
    if config is None:
        config = DistributedConfig.from_env()

    def _env_num(name, cast, default):
        v = os.environ.get(name)
        return cast(v) if v is not None else default

    attempts = (rendezvous_attempts if rendezvous_attempts is not None
                else _env_num("TPU_SYNCBN_RENDEZVOUS_ATTEMPTS", int, 3))
    timeout_s = (rendezvous_timeout_s if rendezvous_timeout_s is not None
                 else _env_num("TPU_SYNCBN_RENDEZVOUS_TIMEOUT_S", float, None))
    backoff_s = (rendezvous_backoff_s if rendezvous_backoff_s is not None
                 else _env_num("TPU_SYNCBN_RENDEZVOUS_BACKOFF_S", float, 1.0))
    # A coordinator address alone (e.g. a stale MASTER_ADDR export from an
    # old GPU script) must not force the multi-host path: require an actual
    # world size > 1, or TPU slice metadata advertising multiple workers
    # (in which case jax.distributed.initialize autodetects everything).
    explicit_multi = (config.num_processes or 1) > 1 or (
        os.environ.get("TPU_SYNCBN_FORCE_DIST") == "1"
    )
    slice_multi = _tpu_slice_is_multihost()
    if explicit_multi:
        kwargs = dict(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
    elif slice_multi:
        # Argless: every parameter is discovered from slice metadata — the
        # TPU-native replacement for env:// rendezvous (README.md:32-35).
        kwargs = {}
    else:
        _initialized = True
        return
    # per-host jitter identity: explicit rank when configured; otherwise
    # slice metadata or the hostname (the argless TPU-slice path discovers
    # rank from metadata, so process_id is None on every host — keying off
    # it alone would put all hosts on an identical retry schedule)
    ident = config.process_id
    if ident is None:
        import socket

        ident = os.environ.get("TPU_WORKER_ID") or socket.gethostname()
    _rendezvous_with_retry(
        kwargs, attempts=attempts, timeout_s=timeout_s, backoff_s=backoff_s,
        jitter_key=f"host{ident}",
    )
    _jax_distributed_active = True
    _initialized = True


def _rendezvous_with_retry(
    kwargs: dict,
    *,
    attempts: int,
    timeout_s: float | None,
    backoff_s: float,
    jitter_key: str,
) -> None:
    """``jax.distributed.initialize(**kwargs)`` under bounded exponential
    backoff with deterministic per-host jitter — N restarted hosts must
    not re-storm a recovering coordinator in lockstep. A per-attempt
    ``initialization_timeout`` is forwarded when this jax supports it."""
    import inspect

    from tpu_syncbn.runtime import resilience

    if timeout_s is not None:
        try:
            params = inspect.signature(jax.distributed.initialize).parameters
        except (TypeError, ValueError):  # builtins without signatures
            params = {}
        if "initialization_timeout" in params:
            kwargs = {**kwargs, "initialization_timeout": int(timeout_s)}

    from tpu_syncbn.obs import telemetry

    def attempt():
        # attempt/failure counters ride telemetry so a flaky coordinator
        # is countable from the bench/summary export, not only from the
        # retry log lines (docs/OBSERVABILITY.md)
        telemetry.count("rendezvous.attempts")
        try:
            jax.distributed.initialize(**kwargs)
        except Exception:
            telemetry.count("rendezvous.failures")
            # a half-open coordination client would poison the next try
            with contextlib.suppress(Exception):
                jax.distributed.shutdown()
            raise

    resilience.retry_with_backoff(
        attempt,
        attempts=attempts,
        base_s=backoff_s,
        key=jitter_key,
        describe="distributed rendezvous",
    )


def _tpu_slice_is_multihost() -> bool:
    """True when TPU slice metadata in the environment advertises more than
    one worker host (the case where ``jax.distributed.initialize`` must run
    before any computation)."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if "," in hostnames:
        return True
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return True
    return False


def is_initialized() -> bool:
    """Analogue of ``torch.distributed.is_initialized`` (consulted by the
    reference's SyncBN sync-or-fallback check,
    ``[torch] nn/modules/batchnorm.py:837-860``)."""
    return _initialized


def shutdown() -> None:
    """Tear down the coordination client (tests / clean exit)."""
    global _initialized, _jax_distributed_active
    if _jax_distributed_active:
        jax.distributed.shutdown()
        _jax_distributed_active = False
    _initialized = False
    _loggers.clear()
    _barrier_cache.clear()


def process_index() -> int:
    """This host's index — the analogue of the recipe's ``RANK`` env var
    (``README.md:34``), except it indexes *hosts*, not chips: TPU is one
    process per host, many chips per process."""
    return jax.process_index()


def process_count() -> int:
    """Number of host processes — analogue of ``WORLD_SIZE`` (``README.md:33``)
    at host granularity."""
    return jax.process_count()


def local_device_count() -> int:
    return jax.local_device_count()


def global_device_count() -> int:
    """Total chips in the slice: the true replica count for data parallelism
    (what the reference calls ``world_size`` = ``nproc_per_node`` × nodes,
    ``README.md:96-100``)."""
    return jax.device_count()


def is_master() -> bool:
    """True on the rank-0 host. The reference's convention: "print losses and
    stuff to the console only on the master process" (``README.md:9``)."""
    return jax.process_index() == 0


def master_print(*args, **kwargs) -> None:
    """``print`` gated to the master host (``README.md:9``)."""
    if is_master():
        print(*args, **kwargs)
        sys.stdout.flush()


class _MasterOnlyFilter(logging.Filter):
    """Drops sub-WARNING records on non-master hosts, deciding at *emit*
    time so master-ness is never frozen before ``initialize()`` has run
    (``jax.process_index`` is only consulted once a record is logged)."""

    def filter(self, record: logging.LogRecord) -> bool:
        return record.levelno >= logging.WARNING or is_master()


def get_logger(name: str = "tpu_syncbn") -> logging.Logger:
    """A logger that emits on the master host only and is silenced (WARNING+)
    elsewhere — the structured version of the rank-0 print convention
    (``README.md:9``)."""
    global _loggers
    if name not in _loggers:
        logger = logging.getLogger(name)
        if not logger.handlers:
            # default stream is stdout (the reference's master-print
            # console convention); TPU_SYNCBN_LOG_STREAM=stderr reroutes
            # for callers whose stdout is a parsed result channel
            # (bench.py sets it so its JSON line owns stdout)
            stream = (
                sys.stderr
                if os.environ.get("TPU_SYNCBN_LOG_STREAM", "").lower()
                == "stderr" else sys.stdout
            )
            handler = logging.StreamHandler(stream)
            handler.setFormatter(
                logging.Formatter(
                    "%(asctime)s [%(levelname)s %(name)s] %(message)s",
                    datefmt="%H:%M:%S",
                )
            )
            logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        if not any(isinstance(f, _MasterOnlyFilter) for f in logger.filters):
            logger.addFilter(_MasterOnlyFilter())
        logger.propagate = False
        _loggers[name] = logger
    return _loggers[name]


def make_mesh(
    axis_sizes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named device mesh over the slice.

    With ``axis_sizes=None`` (the common case) this returns the pure
    data-parallel mesh: one ``'data'`` axis spanning every chip — the
    TPU-native form of the reference's process group of N single-GPU
    replicas (``README.md:5, 96-100``). Arbitrary extra axes (``'model'``
    etc.) may be requested; a size of ``-1`` on at most one axis means
    "everything left", like a reshape wildcard.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = {DATA_AXIS: n}
    names = tuple(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if any(s != -1 and s < 1 for s in sizes):
        raise ValueError(f"mesh axis sizes must be positive (or -1): {axis_sizes}")
    wild = [i for i, s in enumerate(sizes) if s == -1]
    if len(wild) > 1:
        raise ValueError("at most one mesh axis may have size -1")
    if wild:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError(f"{n} devices not divisible by fixed axes {axis_sizes}")
        sizes[wild[0]] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(
            f"mesh axes {dict(zip(names, sizes))} do not cover {n} devices"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, names)


def data_parallel_mesh(num_replicas: int | None = None) -> Mesh:
    """The framework's default mesh: ``('data',)`` over all chips (or the
    first ``num_replicas`` chips, for tests that model a smaller world)."""
    devices = jax.devices()
    if num_replicas is not None:
        if num_replicas > len(devices):
            raise ValueError(
                f"requested {num_replicas} replicas but only "
                f"{len(devices)} devices are present"
            )
        devices = devices[:num_replicas]
    return make_mesh({DATA_AXIS: len(devices)}, devices=devices)


_barrier_cache: dict = {}


def barrier(name: str = "barrier") -> None:
    """Block until every replica reaches this point.

    The reference gets barriers implicitly from blocking NCCL collectives.
    Here: multi-host uses the coordination-service barrier
    (``multihost_utils.sync_global_devices``); single-host runs a cached,
    jit-compiled sum over a local-device-sharded array and blocks on it,
    forcing a cross-device AllReduce to complete. The jitted fn and mesh
    are cached so repeated barriers don't retrace.
    """
    if process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
        return
    key = tuple(jax.local_devices())
    if key not in _barrier_cache:
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh({DATA_AXIS: len(key)}, devices=key)
        fn = jax.jit(
            jax.numpy.sum, out_shardings=NamedSharding(mesh, P())
        )
        _barrier_cache[key] = (mesh, fn)
    mesh, fn = _barrier_cache[key]
    from jax.sharding import NamedSharding, PartitionSpec as P

    ones = jax.numpy.ones((len(key),), dtype=jax.numpy.int32)
    sharded = jax.device_put(ones, NamedSharding(mesh, P(DATA_AXIS)))
    fn(sharded).block_until_ready()
