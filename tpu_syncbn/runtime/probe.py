"""Backend probing that survives a *hung* accelerator plugin.

The reference recipe assumes a working NCCL backend and fails fast when it
is absent. The TPU-tunnel equivalent failure mode here is worse than a
raise: a registered-but-dead PJRT plugin makes ``jax.devices()`` block
forever, which no in-process ``except`` clause can catch. Every driver
entry point (``bench.py``, ``__graft_entry__.dryrun_multichip``) therefore
probes the backend in a **subprocess with a hard timeout** before touching
jax in its own process, and falls back to the CPU platform when the
accelerator is unusable or too small.

Env overrides:
  ``TPU_SYNCBN_FORCE_CPU=1``      skip the probe, force the CPU platform
  ``TPU_SYNCBN_PROBE_TIMEOUT=s``  probe timeout in seconds (default 150:
                                  a live-but-contended tunnel can need
                                  >75s to claim the chip, while the dead
                                  case still leaves room for the CPU
                                  fallback inside a driver budget)
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import NamedTuple, Optional


class BackendInfo(NamedTuple):
    platform: str
    device_count: int


# The axon sitecustomize force-selects its platform via jax.config at
# interpreter start, which beats the JAX_PLATFORMS env var; re-assert the
# env's explicit choice through jax.config so a driver that already forced
# cpu gets a fast, honest probe instead of a doomed accelerator attempt.
_PROBE_CODE = (
    "import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
    "p and jax.config.update('jax_platforms', p); "
    "ds = jax.devices(); "
    "print('PROBE', ds[0].platform, len(ds), flush=True)"
)


# One probe per process: the answer cannot change within a process (env
# and sitecustomize are fixed at interpreter start), and a dead-tunnel
# probe costs its full timeout — a driver calling entry() then
# dryrun_multichip() must not pay it twice. Keyed by nothing; holds the
# last result including the failure sentinel.
_probe_cache: dict = {}


def probe_backend(timeout: Optional[float] = None) -> Optional[BackendInfo]:
    """Ask, in a throwaway subprocess, what ``jax.devices()`` would return
    under the current environment. Returns ``None`` if the backend raises
    OR hangs past ``timeout`` — the latter is the axon tunnel's observed
    failure mode and the reason this cannot be an in-process try/except.
    Caches its result for the life of the process.
    """
    if "result" in _probe_cache:
        return _probe_cache["result"]
    t0 = time.perf_counter()
    result = _probe_uncached(timeout)
    # probe latency + outcome ride telemetry so a CPU fallback is
    # diagnosable from the bench JSON's telemetry block, not only from
    # the stderr notice (docs/OBSERVABILITY.md)
    from tpu_syncbn.obs import telemetry

    telemetry.set_gauge("probe.latency_s", time.perf_counter() - t0)
    telemetry.count("probe.ok" if result is not None else "probe.failed")
    if result is not None:
        telemetry.set_gauge("probe.device_count", result.device_count)
    _probe_cache["result"] = result
    return result


def _probe_uncached(timeout: Optional[float]) -> Optional[BackendInfo]:
    if timeout is None:
        timeout = float(os.environ.get("TPU_SYNCBN_PROBE_TIMEOUT", "150"))
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    if proc.returncode != 0:
        return None
    for line in proc.stdout.splitlines():
        parts = line.split()
        if len(parts) == 3 and parts[0] == "PROBE":
            return BackendInfo(platform=parts[1], device_count=int(parts[2]))
    return None


def _backend_initialized() -> bool:
    """Has THIS process already initialized a jax backend? (After that,
    platform/XLA_FLAGS changes silently do nothing — fail loudly instead.)"""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge.backends_are_initialized())
    except Exception:
        return False


def force_cpu(min_devices: int = 1) -> None:
    """Select the CPU platform *before* the in-process backend initializes,
    with at least ``min_devices`` virtual host devices.

    Both the env var and the config update are required: the axon
    sitecustomize pins ``jax_platforms`` via ``jax.config`` at interpreter
    start, so the env var alone loses; and ``XLA_FLAGS`` is only read at
    first backend initialization, so this must run before any
    ``jax.devices()`` call in this process. If the backend is already
    live and does not satisfy the request, this raises instead of
    returning a fallback that silently never happens.
    """
    if _backend_initialized():
        import jax

        if jax.default_backend() != "cpu" or len(jax.devices()) < min_devices:
            raise RuntimeError(
                "cannot fall back to CPU: this process already initialized "
                f"the '{jax.default_backend()}' backend with "
                f"{len(jax.devices())} device(s) (< {min_devices} requested "
                "or wrong platform). Call ensure_backend(min_devices) "
                "before any jax computation in the process."
            )
        return
    os.environ["JAX_PLATFORMS"] = "cpu"
    if min_devices > 1:
        os.environ["XLA_FLAGS"] = _merge_device_count_flag(
            os.environ.get("XLA_FLAGS", ""), min_devices
        )
    import jax

    jax.config.update("jax_platforms", "cpu")


def _merge_device_count_flag(flags: str, min_devices: int) -> str:
    """Ensure ``--xla_force_host_platform_device_count`` is present with at
    least ``min_devices`` (keeping a larger existing value)."""
    token = "--xla_force_host_platform_device_count="
    parts = [p for p in flags.split() if not p.startswith(token)]
    existing = next(
        (int(p[len(token):]) for p in flags.split() if p.startswith(token)),
        0,
    )
    parts.append(token + str(max(existing, min_devices)))
    return " ".join(parts)


def enable_persistent_compilation_cache() -> Optional[str]:
    """Point XLA's persistent compilation cache at a stable directory so
    recompiles of identical programs are disk hits. This is what makes
    short accelerator-tunnel windows usable: a benchmark that compiled
    ResNet-50 in one window re-loads the binary in the next instead of
    burning the window compiling again. Keyed by HLO + compile options +
    backend, so it is correctness-safe by construction.

    Honors an explicit ``JAX_COMPILATION_CACHE_DIR``; set
    ``TPU_SYNCBN_NO_COMPILE_CACHE=1`` to disable. Returns the directory
    in use, or None when disabled.
    """
    if os.environ.get("TPU_SYNCBN_NO_COMPILE_CACHE") == "1":
        return None
    from tpu_syncbn import compat

    if not compat.HAS_VMA:
        # Pre-VMA jax (0.4.x): REPRODUCED returning wrong values from a
        # warm cache directory (a GANTrainer restored into a fresh
        # trainer computed a different loss with the cache on; fresh
        # cache dirs behaved, the accumulated one did not — consistent
        # with entries half-written by SIGKILLed runs being deserialized
        # without validation on this jax). Silent numerical corruption is
        # strictly worse than recompiling; stay off on this toolchain.
        return None
    path = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if path is None:
        # Cached entries are deserialized compiled executables, so the
        # directory must not be plantable by another local user. A /tmp
        # path (even uid-suffixed) can be pre-created by anyone; default
        # to a user-owned location instead and refuse anything we don't
        # exclusively own.
        base = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser(
            "~/.cache"
        )
        path = os.path.join(base, "tpu_syncbn", "xla")
        try:
            os.makedirs(path, mode=0o700, exist_ok=True)
            st = os.stat(path)
            if st.st_uid != os.getuid() or (st.st_mode & 0o022):
                print(
                    f"[tpu_syncbn.probe] compile cache dir {path} is not "
                    "exclusively user-owned (uid/permission check failed); "
                    "persistent cache disabled",
                    file=sys.stderr,
                    flush=True,
                )
                return None
        except OSError:
            return None
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # jax's default floor (1s) skips every mid-size program; 0.25s catches
    # the suite's sharded-step compiles without persisting thousands of
    # trivial sub-ms jits
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ.get("TPU_SYNCBN_CACHE_MIN_COMPILE_S", "0.25")),
    )
    return path


def ensure_backend(min_devices: int = 1) -> BackendInfo:
    """Guarantee a *usable* jax backend with >= ``min_devices`` devices,
    probing the accelerator first and falling back to (virtual) CPU
    devices when it is dead, hung, or too small. Returns what the probe
    (or the fallback decision) established; call before first jax backend
    touch in the process. Also enables the persistent compilation cache
    (see :func:`enable_persistent_compilation_cache`).
    """
    from tpu_syncbn.obs import telemetry

    enable_persistent_compilation_cache()
    if os.environ.get("TPU_SYNCBN_FORCE_CPU") == "1":
        telemetry.count("probe.forced_cpu")
        force_cpu(min_devices)
        return BackendInfo("cpu", min_devices)
    # Mirror _PROBE_CODE in-process: the sitecustomize's jax.config pin
    # beats the JAX_PLATFORMS env var, so a successful "cpu" probe under
    # JAX_PLATFORMS=cpu would otherwise still leave THIS process about to
    # initialize the (possibly hung) accelerator platform.
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms:
        import jax

        jax.config.update("jax_platforms", env_platforms)
    info = probe_backend()
    if info is None:
        print(
            "[tpu_syncbn.probe] accelerator backend unusable (probe failed "
            "or timed out); forcing CPU platform",
            file=sys.stderr,
            flush=True,
        )
        telemetry.count("probe.cpu_fallback")
        force_cpu(min_devices)
        return BackendInfo("cpu", min_devices)
    if info.device_count < min_devices:
        telemetry.count("probe.cpu_fallback")
        print(
            f"[tpu_syncbn.probe] {info.platform} offers {info.device_count} "
            f"device(s) < required {min_devices}; forcing CPU platform with "
            f"{min_devices} virtual devices",
            file=sys.stderr,
            flush=True,
        )
        force_cpu(min_devices)
        return BackendInfo("cpu", min_devices)
    return info
