"""Fault-tolerant training runtime: preemption, stalls, retries.

The paper's recipe assumes every worker, every rendezvous, and every step
succeeds; on real slices preemption, flaky coordinator DNS, hung data
workers, and NaN blow-ups are the common case. This module is the host-side
resilience layer (docs/RESILIENCE.md is the failure-mode → behavior map):

* :class:`PreemptionGuard` — SIGTERM/SIGINT become a *checkpoint request at
  the next step boundary* instead of a mid-step kill (the Cloud TPU
  preemption contract: a grace window after SIGTERM, then SIGKILL).
* :class:`Watchdog` / :func:`stall_guard` — a collective or data fetch that
  stalls past a deadline dumps per-host diagnostics (thread stacks, device
  and process identity) and surfaces a :class:`StallError` rather than
  hanging the job silently until the scheduler reaps it.
* :func:`retry_with_backoff` — bounded exponential backoff with
  *deterministic* jitter (keyed, no wall-clock randomness) shared by the
  rendezvous retry in ``runtime.distributed.initialize``.
* :class:`ResilientLoop` — composes the above with the manifest-verified
  checkpoint store (``utils.checkpoint``) and the trainer's on-device
  divergence guard into a preemption-safe step loop with
  ``resume_latest`` orchestration and a ``restore_last_good`` policy.

Everything here is host-level control flow: no jax tracing, usable with any
trainer exposing ``state_dict``/``load_state_dict``/``train_step``.
"""

from __future__ import annotations

import contextlib
import io
import os
import signal
import sys
import threading
import time
import traceback
import zlib
from typing import Any, Callable, Iterable, Iterator

from tpu_syncbn.runtime import distributed as dist


class StallError(RuntimeError):
    """A step collective or data fetch exceeded its watchdog deadline."""


# ---------------------------------------------------------------------------
# preemption


class PreemptionGuard:
    """Convert SIGTERM/SIGINT into a polite "checkpoint at the next step
    boundary, then exit" request.

    Usage::

        with PreemptionGuard() as guard:
            for batch in loader:
                dp.train_step(batch)
                if guard.preempted:
                    save_checkpoint(ckpt_dir, step, dp.state_dict())
                    break

    The first signal only sets a flag (checked via :attr:`preempted` at
    step boundaries — never mid-step, so the saved state is a step-exact
    snapshot). A *second* signal re-raises through the previously
    installed handler: an impatient operator's double Ctrl-C still kills
    the process immediately.

    Signal handlers are process-global and only installable from the main
    thread; constructing the guard elsewhere raises ``ValueError`` (from
    ``signal.signal``) rather than silently not protecting anything.
    """

    def __init__(
        self,
        signals: tuple = (signal.SIGTERM, signal.SIGINT),
        *,
        callback: Callable[[int], None] | None = None,
    ):
        self._signals = tuple(signals)
        self._callback = callback
        self._subscribers: list[Callable[[int], None]] = []
        self._event = threading.Event()
        self._prev: dict[int, Any] = {}
        self._received: int | None = None
        self._installed = False

    def subscribe(self, fn: Callable[[int], None]) -> None:
        """Add a listener invoked (after the construction ``callback``)
        on the FIRST signal delivery. Lets late-attached components —
        e.g. a :class:`~tpu_syncbn.serve.publish.SwapController` that
        must drain a mid-swap engine — hook the same guard the training
        loop and batcher already share. Listener exceptions are
        swallowed: a broken subscriber must not turn a polite drain
        into a crash inside a signal handler."""
        self._subscribers.append(fn)

    # -- handler ----------------------------------------------------------

    def _handle(self, signum, frame):
        if self._event.is_set():
            # second delivery: defer to the original disposition (usually
            # fatal) — the operator means it
            self._restore()
            os.kill(os.getpid(), signum)
            return
        self._received = signum
        self._event.set()
        dist.get_logger("tpu_syncbn.resilience").warning(
            "received signal %d: will checkpoint at the next step boundary "
            "and exit", signum,
        )
        if self._callback is not None:
            self._callback(signum)
        for fn in self._subscribers:
            with contextlib.suppress(Exception):
                fn(signum)

    def __enter__(self) -> "PreemptionGuard":
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handle)
        self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        self._restore()

    def _restore(self) -> None:
        if self._installed:
            for s, prev in self._prev.items():
                with contextlib.suppress(Exception):
                    signal.signal(s, prev)
            self._installed = False

    # -- queries ----------------------------------------------------------

    @property
    def preempted(self) -> bool:
        """True once a shutdown signal has been received."""
        return self._event.is_set()

    @property
    def signum(self) -> int | None:
        return self._received

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)


# ---------------------------------------------------------------------------
# watchdog


def dump_stacks(header: str = "") -> str:
    """Per-host diagnostic snapshot: process identity, device world, and
    every Python thread's stack — what you need from EACH host to see
    which rank a stalled collective is waiting on."""
    import jax

    buf = io.StringIO()
    if header:
        buf.write(header + "\n")
    try:
        buf.write(
            f"host {dist.process_index()}/{dist.process_count()} "
            f"({jax.local_device_count()} local / {jax.device_count()} "
            "global devices)\n"
        )
    except Exception as e:  # diagnostics must never throw past themselves
        buf.write(f"device world unavailable: {e}\n")
    frames = sys._current_frames()
    threads = {t.ident: t for t in threading.enumerate()}
    for ident, frame in frames.items():
        t = threads.get(ident)
        name = t.name if t else f"thread-{ident}"
        buf.write(f"--- thread {name} ---\n")
        buf.write("".join(traceback.format_stack(frame)))
    return buf.getvalue()


class Watchdog:
    """Deadline monitor for the step loop: if :meth:`pat` is not called
    within ``deadline_s``, dump per-host diagnostics (once per stall) and
    invoke ``on_stall`` — by default logging the dump at ERROR so a hung
    collective leaves evidence on every host instead of an opaque freeze.

    Pass ``on_stall=` + a raising callable (or use :func:`stall_guard` for
    data iterators, which raises :class:`StallError` in the *consumer*)
    when the stall should abort rather than just report. The monitor is a
    daemon thread; ``close()`` (or context-manager exit) stops it.
    """

    def __init__(
        self,
        deadline_s: float,
        *,
        name: str = "step",
        on_stall: Callable[[str], None] | None = None,
        poll_s: float | None = None,
        start_armed: bool = True,
    ):
        """``start_armed=False`` defers the deadline clock until the
        first :meth:`pat` — for loops whose first iteration legitimately
        dwarfs the steady-state deadline (XLA compiling the step on a
        cold start would otherwise read as a stall)."""
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self.name = name
        self._on_stall = on_stall
        self._poll_s = poll_s if poll_s is not None else min(
            0.05, deadline_s / 4
        )
        self._last = time.monotonic() if start_armed else None
        self._stalled_since: float | None = None
        self.stall_count = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"watchdog-{name}", daemon=True
        )
        self._thread.start()

    def pat(self) -> None:
        """Mark liveness (call once per step / per batch)."""
        self._last = time.monotonic()
        self._stalled_since = None

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            if self._last is None:
                continue  # not armed yet (start_armed=False, no pat)
            idle = time.monotonic() - self._last
            if idle > self.deadline_s and self._stalled_since is None:
                self._stalled_since = self._last
                self.stall_count += 1
                # tag the dump with the most recently opened trace span
                # (the monitor thread has no span stack of its own) so a
                # Perfetto trace and this event log join on span id
                from tpu_syncbn.obs import flightrec, telemetry, tracing

                span_id = tracing.latest_open_span_id()
                telemetry.count("resilience.watchdog_stalls")
                tracing.instant(
                    "watchdog_stall", watchdog=self.name,
                    idle_s=round(idle, 2),
                    **({"span_id": span_id} if span_id is not None else {}),
                )
                tag = f", trace_span={span_id}" if span_id is not None else ""
                diag = dump_stacks(
                    f"WATCHDOG: {self.name!r} stalled for {idle:.1f}s "
                    f"(deadline {self.deadline_s}s{tag})"
                )
                logger = dist.get_logger("tpu_syncbn.resilience")
                logger.error("%s", diag)
                # the stack dump says where THIS host is stuck; the
                # incident bundle says what the whole process was doing
                # in the seconds before (docs/OBSERVABILITY.md)
                flightrec.trigger("watchdog_stall", {
                    "watchdog": self.name, "idle_s": round(idle, 2),
                    "deadline_s": self.deadline_s,
                    **({"span_id": span_id} if span_id is not None
                       else {}),
                })
                if self._on_stall is not None:
                    with contextlib.suppress(Exception):
                        self._on_stall(diag)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)

    def __enter__(self) -> "Watchdog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def stall_guard(
    iterator: Iterable, deadline_s: float, *, name: str = "batch"
) -> Iterator:
    """Wrap a (possibly hanging) batch iterator so the consumer NEVER
    blocks past ``deadline_s`` on one item: a fetcher thread pulls from
    the source while the consumer waits on a queue with a timeout, raising
    :class:`StallError` (with per-host stack diagnostics logged) when the
    deadline passes — a hung data worker becomes a loud, catchable fault
    at the step boundary instead of an indefinite hang.

    The fetcher prefetches at most one item. Once the consumer is done —
    StallError raised, generator closed, or the source exhausted — a stop
    flag makes the fetcher exit as soon as its (possibly still-hung)
    ``next()`` returns, rather than lingering blocked on the queue: an
    abandoned guard must not keep pulling from a source iterator the
    caller may hand to a fresh guard on retry. The one batch in flight at
    stall time is dropped with the stalled fetch; only a fetcher stuck
    inside the source forever remains (daemon — dies with the process).
    """
    import queue as _queue

    if deadline_s <= 0:
        raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
    q: Any = _queue.Queue(maxsize=1)
    DONE, ERR = object(), object()
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    def fetch():
        try:
            for item in iterator:
                if not put(("ok", item)):
                    return  # consumer gone: do not touch the source again
        except BaseException as e:
            put((ERR, e))
            return
        put((DONE, None))

    t = threading.Thread(target=fetch, name=f"stall-guard-{name}",
                         daemon=True)
    t.start()
    try:
        while True:
            try:
                tag, payload = q.get(timeout=deadline_s)
            except _queue.Empty:
                from tpu_syncbn.obs import flightrec, telemetry, tracing

                span_id = tracing.latest_open_span_id()
                telemetry.count("resilience.data_stalls")
                tracing.instant(
                    "data_stall", source=name,
                    **({"span_id": span_id} if span_id is not None else {}),
                )
                tag = (f" (trace_span={span_id})"
                       if span_id is not None else "")
                diag = dump_stacks(
                    f"WATCHDOG: {name!r} fetch exceeded {deadline_s}s{tag}"
                )
                dist.get_logger("tpu_syncbn.resilience").error("%s", diag)
                flightrec.trigger("watchdog_stall", {
                    "source": name, "deadline_s": deadline_s,
                    "stall": "data_fetch",
                })
                raise StallError(
                    f"{name} fetch exceeded the {deadline_s}s watchdog "
                    "deadline"
                ) from None
            if tag is DONE:
                return
            if tag is ERR:
                raise payload
            yield payload
    finally:
        stop.set()


# ---------------------------------------------------------------------------
# retry / backoff


def backoff_delays(
    attempts: int,
    *,
    base_s: float = 1.0,
    max_s: float = 30.0,
    jitter: float = 0.25,
    key: str = "",
) -> list[float]:
    """The ``attempts - 1`` sleep durations between retries: exponential
    (``base * 2**i`` capped at ``max_s``) with ±``jitter`` fractional
    spread. Jitter is *deterministic* — keyed off ``key`` (e.g. host
    index) via CRC32, not wall-clock RNG — so retries are reproducible
    under the fault harness yet de-synchronized across hosts (the point
    of jitter: N preempted hosts must not re-storm the coordinator in
    lockstep)."""
    delays = []
    for i in range(max(0, attempts - 1)):
        d = min(max_s, base_s * (2 ** i))
        # unit-interval hash of (key, attempt): stable across runs
        u = (zlib.crc32(f"{key}:{i}".encode()) & 0xFFFFFFFF) / 0xFFFFFFFF
        delays.append(d * (1.0 + jitter * (2.0 * u - 1.0)))
    return delays


def retry_with_backoff(
    fn: Callable[[], Any],
    *,
    attempts: int = 3,
    base_s: float = 1.0,
    max_s: float = 30.0,
    jitter: float = 0.25,
    key: str = "",
    retry_on: tuple = (Exception,),
    describe: str = "operation",
    sleep: Callable[[float], None] | None = None,
) -> Any:
    """Call ``fn`` up to ``attempts`` times with :func:`backoff_delays`
    between failures; the final failure re-raises. Each retry is logged
    with the exception — a rendezvous that needed 3 tries is an incident
    worth seeing in the log even when it eventually succeeds."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    if sleep is None:
        sleep = time.sleep  # late-bound: patchable via resilience.time
    delays = backoff_delays(
        attempts, base_s=base_s, max_s=max_s, jitter=jitter, key=key
    )
    logger = dist.get_logger("tpu_syncbn.resilience")
    for i in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if i == attempts - 1:
                raise
            logger.warning(
                "%s failed (attempt %d/%d: %s: %s); retrying in %.2fs",
                describe, i + 1, attempts, type(e).__name__, e, delays[i],
            )
            sleep(delays[i])
    raise AssertionError("unreachable")


# ---------------------------------------------------------------------------
# orchestration


def _default_counters():
    from tpu_syncbn.obs.telemetry import CounterGroup

    # prefix="resilience": every bump mirrors into the process telemetry
    # registry (as resilience.<event>) when telemetry is enabled, so
    # recovery events ride the same export path as step/loader/checkpoint
    # metrics — while the loop's own summary() works unconditionally
    return CounterGroup("resilience")


class ResilientLoop:
    """Preemption-safe training driver over any trainer with the
    ``state_dict``/``load_state_dict``/``train_step`` surface (the
    ``DataParallel``/``GANTrainer`` contract).

    Composes the resilience primitives into the loop the examples run::

        loop = ResilientLoop(dp, ckpt_dir, ckpt_every=100)
        start = loop.resume()                  # newest VERIFIED checkpoint
        summary = loop.run(batches)            # SIGTERM-safe, NaN-guarded

    Behavior (knobs → docs/RESILIENCE.md):

    * resume: :meth:`resume` restores the newest *verified* checkpoint
      (``utils.checkpoint`` manifest fallback) and returns the step to
      continue from (0 when none exists).
    * preemption: SIGTERM/SIGINT set a flag; the loop finishes the
      in-flight step, saves a checkpoint at the boundary, and returns with
      ``summary["preempted"] = True`` — exit code stays 0, the restarted
      job resumes exactly there.
    * divergence: when the trainer was built with
      ``divergence_guard="restore_last_good"``, a step reporting a
      non-finite loss/grad (the on-device ``nonfinite`` metric) reloads
      the last verified checkpoint; ``max_restores`` bounds the
      thrash-loop (beyond it the loop raises ``FloatingPointError``).
      ``skip_step``/``halve_lr`` policies are entirely on-device and need
      no host cooperation (the loop just counts them).
    * liveness: ``step_deadline_s`` arms a :class:`Watchdog` patted every
      step; a stall dumps per-host stacks. Data stalls should be guarded
      at the iterator with :func:`stall_guard` (raises, so the loop can
      checkpoint-and-exit via the normal exception path).
    """

    #: Bounded wait for async checkpoint writes while a training failure
    #: is already propagating: long enough for any healthy write (the
    #: 204MB bench payload serializes in ~1s), short enough that a
    #: wedged writer (stuck filesystem) can't turn a StallError into an
    #: indefinite hang with the watchdog already disarmed.
    _EXC_FLUSH_TIMEOUT_S = 60.0

    def __init__(
        self,
        trainer,
        ckpt_dir: str,
        *,
        ckpt_every: int = 100,
        keep: int = 3,
        max_restores: int = 3,
        step_deadline_s: float | None = None,
        counters=None,
        scan_steps: int = 1,
        async_checkpoint: bool = False,
        publish_dir: str | None = None,
        publish_every: int | None = None,
        publish_keep: int = 3,
        autopilot=None,
    ):
        """``scan_steps=K > 1`` drives the fused multi-step path
        (docs/PERFORMANCE.md): ``batches`` must then yield K-stacked
        chunks (``data.device_prefetch(scan_steps=K)``) and the loop
        calls ``trainer.train_steps_batches`` once per chunk — one host
        dispatch per K steps, with preemption, checkpoint cadence, and
        divergence policies honored at chunk boundaries (the on-device
        guard still rolls back each bad step *inside* the chunk; the
        host sees the chunk's stacked ``nonfinite`` metrics afterward).
        ``step_deadline_s`` stays a per-STEP deadline: the loop arms its
        watchdog at ``step_deadline_s * scan_steps`` since it can only
        pat once per chunk.

        ``async_checkpoint=True`` routes saves through
        ``utils.checkpoint.AsyncCheckpointer``: the loop pays only the
        state snapshot; serialization + manifest + atomic write happen
        in a background thread, and the loop **flushes pending writes on
        every exit path** — the PreemptionGuard boundary checkpoint is
        durable before the process yields to SIGKILL.

        ``publish_dir`` additionally emits manifest-verified *serving*
        publications (``utils.checkpoint.publish_version``) every
        ``publish_every`` steps (default: ``ckpt_every``): a versioned
        inference tree (``{"params", "rest"}`` — BN running stats ride
        along) that a serving process hot-swaps in via
        ``serve.publish.SwapController.swap_from_publication``. Under
        ``zero=True`` the flat shards are gathered first (the durable
        cross-process path is host serialization by nature; the
        no-host-gather on-mesh path is the *in-process*
        ``swap_from_trainer``). Publications follow the checkpoint
        transport: async when ``async_checkpoint=True``.

        ``autopilot`` attaches a
        :class:`~tpu_syncbn.runtime.autopilot.Autopilot`: the loop
        drives its :meth:`~tpu_syncbn.runtime.autopilot.Autopilot.on_chunk`
        at every chunk boundary (suppressed, and recorded as
        suppressed, while a divergence rollback is recovering), mirrors
        its live ``scan_k`` into ``self.scan_steps``, and rescales the
        watchdog deadline to the live K. Feed the loop through
        :func:`~tpu_syncbn.runtime.autopilot.chunked_batches` so the
        data side follows the same K."""
        if ckpt_every < 1:
            raise ValueError(f"ckpt_every must be >= 1, got {ckpt_every}")
        if scan_steps < 1:
            raise ValueError(f"scan_steps must be >= 1, got {scan_steps}")
        if publish_every is not None and publish_every < 1:
            raise ValueError(
                f"publish_every must be >= 1, got {publish_every}"
            )
        self.trainer = trainer
        self.ckpt_dir = ckpt_dir
        self.ckpt_every = ckpt_every
        self.keep = keep
        self.max_restores = max_restores
        self.step_deadline_s = step_deadline_s
        self.scan_steps = scan_steps
        self.autopilot = autopilot
        self.publish_dir = publish_dir
        self.publish_every = (
            int(publish_every) if publish_every is not None else ckpt_every
        )
        self.publish_keep = publish_keep
        self.counters = counters if counters is not None else _default_counters()
        self.step = 0
        #: True while a divergence rollback is in flight (restore issued,
        #: no finite step completed since) — surfaced on /readyz via
        #: :meth:`readiness` so a balancer stops routing to a host that
        #: is busy recovering state.
        self.recovering = False
        self._guard: PreemptionGuard | None = None
        self._async = None
        if async_checkpoint:
            from tpu_syncbn.utils.checkpoint import AsyncCheckpointer

            self._async = AsyncCheckpointer(keep=keep)
        self._log = dist.get_logger("tpu_syncbn.resilience")

    # -- checkpoint plumbing ----------------------------------------------

    def flush_checkpoints(self, timeout: float | None = None) -> bool:
        """Block until async checkpoint writes (if any) are durable —
        called on every ``run()`` exit path, and before any read of the
        checkpoint directory (resume/restore), so a pending write can
        neither be lost to an exit nor raced by a load. Returns False
        when ``timeout`` expired with writes still in flight (the
        directory must then NOT be trusted as current)."""
        if self._async is not None:
            return self._async.flush(timeout)
        return True

    def close(self) -> None:
        """Flush and stop the async checkpoint worker (no-op without
        ``async_checkpoint=True``). Idempotent; a loop the caller keeps
        re-running can stay open, but one built per restart attempt
        should be closed (or used as a context manager) so worker
        threads don't accumulate."""
        if self._async is not None:
            self._async.close()

    def __enter__(self) -> "ResilientLoop":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def readiness(self) -> tuple[bool, dict]:
        """The loop's ``/readyz`` contribution (registered as the
        ``train`` hook while :meth:`run` is active): not ready once
        preemption has been signaled (the process is about to
        checkpoint-and-exit) or while a divergence rollback is in
        flight. The detail block carries the live step counter, so a
        probe can also see *where* the loop is."""
        guard = self._guard
        preempted = bool(guard.preempted) if guard is not None else False
        ok = not preempted and not self.recovering
        return ok, {
            "step": self.step,
            "preempted": preempted,
            "recovering": self.recovering,
        }

    def resume(self) -> int:
        """Restore the newest verified checkpoint (if any); returns the
        step training should continue from."""
        from tpu_syncbn.parallel.trainer import resume_latest

        self.flush_checkpoints()
        self.step = resume_latest(self.trainer, self.ckpt_dir)
        if self.step:
            self.counters.bump("resumes")
        return self.step

    def save(self) -> None:
        from tpu_syncbn.utils import checkpoint as ckpt

        if self._async is not None:
            self._async.save(
                self.ckpt_dir, self.step, self.trainer.state_dict(),
                keep=self.keep,
            )
        else:
            ckpt.save_checkpoint(
                self.ckpt_dir, self.step, self.trainer.state_dict(),
                keep=self.keep,
            )
        self.counters.bump("checkpoints")

    def publish(self) -> None:
        """Emit a manifest-verified serving publication of the current
        params at ``publish_dir``, versioned by the step counter (no-op
        without ``publish_dir``). The tree is the inference pair
        ``{"params", "rest"}``; under ZeRO the flat shards are gathered
        into the full pytree first (durable host path — the on-mesh
        redistribution serves the in-process swap instead)."""
        if self.publish_dir is None:
            return
        from tpu_syncbn.utils import checkpoint as ckpt

        trainer = self.trainer
        if getattr(trainer, "zero", False):
            from tpu_syncbn.parallel.zero import unshard_params

            params = unshard_params(trainer._layout, trainer._param_store)
        else:
            params = trainer._param_store
        tree = {"params": params, "rest": getattr(trainer, "rest", {})}
        if self._async is not None:
            self._async.publish(
                self.publish_dir, self.step, tree, keep=self.publish_keep,
            )
        else:
            ckpt.publish_version(
                self.publish_dir, self.step, tree,
                keep=self.publish_keep, step=self.step,
            )
        self.counters.bump("publishes")

    def _restore_last_good(self) -> None:
        from tpu_syncbn.parallel.trainer import resume_latest
        from tpu_syncbn.utils import checkpoint as ckpt

        self.flush_checkpoints()
        if not ckpt.available_steps(self.ckpt_dir):
            # nothing durable yet (divergence before the first save):
            # there is no state to restore — but the on-device guard
            # already rolled the bad update back, so degrading to
            # skip-step semantics (step counter untouched) is safe
            self.counters.bump("divergence_skips_without_checkpoint")
            self._log.warning(
                "non-finite loss/grads at step %d with no checkpoint to "
                "restore; on-device guard already skipped the update — "
                "continuing", self.step,
            )
            return
        restored = resume_latest(self.trainer, self.ckpt_dir)
        # the restored residual (compressed-collective error feedback)
        # encodes quantization error of the unwound trajectory — zero it
        # so the recovered run doesn't replay stale updates; an ordinary
        # resume (no divergence) keeps the checkpointed residual
        reset = getattr(self.trainer, "reset_compression_residual", None)
        if callable(reset):
            reset()
        self.counters.bump("divergence_restores")
        # not-ready until a finite step lands on the restored state
        # (cleared in run(); read by /readyz through readiness())
        self.recovering = True
        # tag the rollback with the current trace span so the Perfetto
        # timeline and this log line correlate (same id in both)
        from tpu_syncbn.obs import flightrec, tracing

        span_id = tracing.latest_open_span_id()
        tracing.instant(
            "divergence_restore", step=self.step, restored_step=restored,
            **({"span_id": span_id} if span_id is not None else {}),
        )
        self._log.warning(
            "non-finite loss/grads at step %d: restored last good "
            "checkpoint (step %d)%s",
            self.step, restored,
            f" (trace_span={span_id})" if span_id is not None else "",
        )
        # the bundle holds the step monitors from the steps BEFORE the
        # blow-up — the evidence a post-mortem of the divergence needs
        flightrec.trigger("divergence_restore", {
            "step": self.step, "restored_step": restored,
            **({"span_id": span_id} if span_id is not None else {}),
        })
        self.step = restored

    # -- the loop ---------------------------------------------------------

    def run(self, batches: Iterable, *, max_steps: int | None = None) -> dict:
        """Drive ``trainer.train_step`` over ``batches`` (or
        ``trainer.train_steps_batches`` over K-stacked chunks when
        ``scan_steps=K > 1``) with preemption, divergence, and liveness
        handling. Returns a summary dict (``steps``, ``preempted``, plus
        the counter snapshot).

        Chunked mode semantics (docs/PERFORMANCE.md): host policies fire
        at chunk boundaries — a SIGTERM landing mid-chunk lets the
        in-flight chunk finish (its K steps are one compiled program),
        then checkpoints and exits; ``ckpt_every`` saves whenever the
        step counter crosses a multiple; ``max_steps`` is checked before
        each chunk, so a run may overshoot it by at most K-1 steps. Any
        async checkpoint writes are flushed on every exit path."""
        import numpy as _np

        from tpu_syncbn.obs import (
            flightrec, numerics as obs_numerics, server as obs_server,
            telemetry,
        )
        from tpu_syncbn.parallel.collectives import DispatchWireTally

        policy = getattr(self.trainer, "divergence_guard", None)
        scanned = self.scan_steps > 1
        preempted = False
        # live monitoring (docs/OBSERVABILITY.md "Live monitoring"):
        # with TPU_SYNCBN_METRICS_PORT set this run answers /metrics,
        # /healthz (step heartbeat below), /readyz (the `train` hook)
        obs_server.start_from_env()
        # flight recorder (docs/OBSERVABILITY.md "Incidents"): with
        # TPU_SYNCBN_FLIGHTREC set this run keeps bounded rings of
        # recent spans/monitors and dumps an incident bundle on a
        # divergence restore, watchdog stall, SLO alert, or /incidentz
        flightrec.install_from_env()
        # memory watermarks (docs/OBSERVABILITY.md "Memory & compile"):
        # with TPU_SYNCBN_MEMWATCH set this run samples device/host
        # memory in the background; pinned-contract pressure dumps a
        # mem_pressure bundle before the allocator OOMs the loop
        from tpu_syncbn.obs import memwatch as obs_memwatch

        obs_memwatch.install_from_env()
        obs_server.register_readiness("train", self.readiness)
        wire_tally = DispatchWireTally()
        # numerics drift/compression telemetry (docs/OBSERVABILITY.md
        # "Numerics & drift"): publishes each step's numerics monitors
        # into the registry once their device values settle (is_ready
        # probe — never a forced host sync on the loop) and fires the
        # numerics_drift incident trigger on a threshold crossing
        numerics_pub = obs_numerics.NumericsPublisher()
        try:
            with contextlib.ExitStack() as stack:
                guard = stack.enter_context(PreemptionGuard())
                self._guard = guard
                watchdog = None
                if self.step_deadline_s is not None:
                    # armed at the first pat: the first step's XLA compile
                    # legitimately dwarfs the steady-state deadline.
                    # Chunked mode pats once per K-step chunk, so the
                    # per-STEP deadline the caller configured scales by K
                    # — a healthy chunk must not read as a stall. The
                    # deadline is recomputed from the LIVE K at every
                    # chunk boundary below: a mid-run K change (the
                    # autopilot's actuator, or manual retuning of
                    # self.scan_steps) must not leave a stale stall
                    # threshold.
                    watchdog = stack.enter_context(
                        Watchdog(self.step_deadline_s * self.scan_steps,
                                 name="train-step", start_armed=False)
                    )
                from tpu_syncbn.obs import stepstats

                steps_run = 0
                # explicit next() so the wait-for-data seam is measurable:
                # each blocking fetch is a "data_wait" span + histogram
                # sample, each step (or fused chunk) a span — the same
                # seams bench.py instruments, so any loop's trace reads
                # the same way
                for batch in stepstats.instrumented_batches(batches):
                    if max_steps is not None and steps_run >= max_steps:
                        break
                    if scanned:
                        with stepstats.timed_span(
                            "scan_chunk", "step.chunk_time_s",
                            step=self.step + 1,
                        ):
                            out = self.trainer.train_steps_batches(batch)
                        k = int(out.loss.shape[0])
                    else:
                        with stepstats.timed_span("step", "step.time_s",
                                                  step=self.step + 1):
                            out = self.trainer.train_step(batch)
                        k = 1
                    self.step += k
                    steps_run += k
                    if watchdog is not None:
                        watchdog.pat()
                    # step heartbeat: /healthz reads the age of this
                    # beat; the gauge gives scrapers the live position
                    obs_server.HEARTBEATS.beat("train")
                    telemetry.set_gauge("train.step", self.step)
                    # step ring: async device scalars recorded as-is
                    # (no host sync here; scalarized at dump time)
                    flightrec.record_step(
                        self.step, metrics=out.metrics,
                        monitors=getattr(out, "monitors", None),
                    )
                    mon = getattr(out, "monitors", None)
                    if scanned and isinstance(mon, dict) and mon:
                        # chunk outputs are (K,)-stacked: publish the
                        # chunk-final slice (lazy device-side indexing,
                        # no host sync)
                        mon = {name: v[-1] for name, v in mon.items()}
                    numerics_pub.publish(self.step, mon)
                    wire_tally.after_dispatch(k)
                    if policy is not None:
                        # scalar for a single step, (K,)-stacked for a
                        # chunk: the sum is the count of skipped steps
                        nonfinite = int(_np.sum(_np.asarray(
                            out.metrics.get("nonfinite", 0.0)
                        )))
                        if nonfinite == 0:
                            # a finite step on (possibly restored) state:
                            # the rollback, if any, is complete — ready
                            self.recovering = False
                        if nonfinite > 0:
                            self.counters.bump("nonfinite_steps", nonfinite)
                            if policy == "restore_last_good":
                                if (self.counters.count("divergence_restores")
                                        >= self.max_restores):
                                    raise FloatingPointError(
                                        "divergence persisted through "
                                        f"{self.max_restores} "
                                        "restore_last_good recoveries — "
                                        "refusing to thrash"
                                    )
                                self._restore_last_good()
                                if self.autopilot is not None:
                                    # the guard owns the process during
                                    # a rollback: the policy step is
                                    # suppressed (and recorded as such)
                                    self.autopilot.on_chunk(
                                        step=self.step, k=k,
                                        recovering=True,
                                    )
                                if guard.preempted:
                                    # the restored state IS the last durable
                                    # checkpoint — exit now rather than burn
                                    # grace-window time on another step
                                    preempted = True
                                    self._log.warning(
                                        "preempted during divergence "
                                        "recovery at step %d; state already "
                                        "durable; exiting cleanly", self.step,
                                    )
                                    break
                                continue
                    if self.autopilot is not None:
                        # chunk-boundary policy step: the only place
                        # knobs turn. The loop mirrors the live K so
                        # max_steps/watchdog accounting follows the
                        # controller; the data side follows through
                        # autopilot.chunked_batches.
                        self.autopilot.on_chunk(
                            step=self.step, k=k,
                            recovering=self.recovering,
                        )
                        if scanned:
                            self.scan_steps = max(
                                1, int(self.autopilot.scan_k)
                            )
                    if (watchdog is not None
                            and self.step_deadline_s is not None):
                        # stale-deadline fix: recompute per chunk from
                        # the current K instead of trusting the value
                        # captured at construction
                        watchdog.deadline_s = (
                            self.step_deadline_s * max(1, self.scan_steps)
                        )
                    if guard.preempted:
                        self.save()
                        preempted = True
                        self._log.warning(
                            "preemption checkpoint written at step %d; "
                            "exiting cleanly", self.step,
                        )
                        break
                    if (self.step // self.ckpt_every
                            != (self.step - k) // self.ckpt_every):
                        self.save()
                    if (self.publish_dir is not None
                            and self.step // self.publish_every
                            != (self.step - k) // self.publish_every):
                        self.publish()
        except BaseException:
            # async writes still get their durability chance, but a
            # flush failure must NOT replace the loop's primary failure
            # (a FloatingPointError/StallError caller handler has to see
            # its exception type), and a wedged writer must not convert
            # it into an indefinite hang — bounded wait, log, propagate
            try:
                if not self.flush_checkpoints(
                        timeout=self._EXC_FLUSH_TIMEOUT_S):
                    self._log.error(
                        "async checkpoint flush still pending after %.0fs "
                        "while a training failure was propagating; "
                        "abandoning the write (checkpoint directory may "
                        "be stale)", self._EXC_FLUSH_TIMEOUT_S,
                    )
            except Exception:
                self._log.exception(
                    "async checkpoint flush failed while a training "
                    "failure was already propagating"
                )
            raise
        finally:
            # the hook must not outlive the loop run: a probe hitting a
            # finished (or crashed) loop should see "no train check",
            # not a stale ready/not-ready claim — and the same for the
            # step heartbeat, which would otherwise read as a stale
            # liveness source and 503 every later /healthz probe
            obs_server.unregister_readiness("train")
            obs_server.HEARTBEATS.clear("train")
            self._guard = None
            try:
                # non-blocking tail drain: publish whatever settled. A
                # BLOCKING flush here could hang forever on the one exit
                # path that matters most (a watchdog stall = a device
                # value that never becomes ready); the clean-exit flush
                # below gets the rest
                numerics_pub.publish(self.step, None)
            except Exception:
                self._log.exception(
                    "numerics publisher drain failed on loop exit"
                )
        # async writes become durable before control leaves the loop — on
        # the preemption path this runs inside the grace window, and a
        # flush error DOES raise here: returning {'preempted': True}
        # over a failed boundary write would claim durability it lacks
        self.flush_checkpoints()
        # clean exit: the device chain has settled (the loop's last step
        # completed), so the blocking numerics drain is safe here and the
        # final steps' drift evidence reaches the registry
        numerics_pub.flush()
        return {
            "steps": steps_run,
            "step": self.step,
            "preempted": preempted,
            **self.counters.summary(),
        }
