"""ctypes bindings for the native C++ runtime components
(``native/libtpu_syncbn_native.so``).

These are the TPU-native homes for the reference's native (C++/CUDA)
non-kernel components (SURVEY §2 "Native?" rows):

* sampler index generation (C++ MT19937 identical to numpy's legacy
  RandomState — the index arithmetic of
  ``[torch] utils/data/distributed.py`` in native code);
* staging ring buffer (the pinned-memory batch staging of
  ``DataLoader(pin_memory=True)``, reference ``README.md:88``);
* TCP key/value store + counters (torch's C++ TCPStore behind
  ``init_method='env://'``, reference ``README.md:32``).

The library is built lazily with ``make`` on first use; every consumer has
a pure-Python fallback, so the framework works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtpu_syncbn_native.so")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            if not os.path.exists(_LIB_PATH):
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR],
                    check=True, capture_output=True, timeout=120,
                )
            lib = ctypes.CDLL(_LIB_PATH)
        except Exception:
            _load_failed = True
            return None
        _configure(lib)
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def _configure(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.tsb_permutation.argtypes = [c.c_uint32, c.c_int64, c.POINTER(c.c_int64)]
    lib.tsb_permutation.restype = None
    lib.tsb_sampler_indices.argtypes = [
        c.c_int64, c.c_int32, c.c_int32, c.c_uint32, c.c_int64,
        c.c_int32, c.c_int32, c.POINTER(c.c_int64),
    ]
    lib.tsb_sampler_indices.restype = c.c_int64

    lib.tsb_ring_create.argtypes = [c.c_int32, c.c_int64]
    lib.tsb_ring_create.restype = c.c_void_p
    lib.tsb_ring_destroy.argtypes = [c.c_void_p]
    lib.tsb_ring_acquire.argtypes = [c.c_void_p, c.POINTER(c.c_void_p)]
    lib.tsb_ring_acquire.restype = c.c_int64
    lib.tsb_ring_commit.argtypes = [c.c_void_p, c.c_int64, c.c_int64]
    lib.tsb_ring_consume.argtypes = [
        c.c_void_p, c.POINTER(c.c_void_p), c.POINTER(c.c_int64)
    ]
    lib.tsb_ring_consume.restype = c.c_int64
    lib.tsb_ring_release.argtypes = [c.c_void_p, c.c_int64]
    lib.tsb_ring_slot_bytes.argtypes = [c.c_void_p]
    lib.tsb_ring_slot_bytes.restype = c.c_int64

    lib.tsb_store_server_start.argtypes = [c.c_uint16, c.POINTER(c.c_uint16)]
    lib.tsb_store_server_start.restype = c.c_void_p
    lib.tsb_store_server_stop.argtypes = [c.c_void_p]
    lib.tsb_store_connect.argtypes = [c.c_char_p, c.c_uint16]
    lib.tsb_store_connect.restype = c.c_int32
    lib.tsb_store_close.argtypes = [c.c_int32]
    lib.tsb_store_set.argtypes = [
        c.c_int32, c.c_char_p, c.POINTER(c.c_uint8), c.c_uint32
    ]
    lib.tsb_store_set.restype = c.c_int32
    lib.tsb_store_get.argtypes = [
        c.c_int32, c.c_char_p, c.POINTER(c.c_uint8), c.c_int64
    ]
    lib.tsb_store_get.restype = c.c_int64
    lib.tsb_store_add.argtypes = [c.c_int32, c.c_char_p, c.c_int64]
    lib.tsb_store_add.restype = c.c_int64


# -- sampler --------------------------------------------------------------


def permutation(seed: int, n: int):
    """numpy ``RandomState(seed).permutation(n)`` computed natively
    (bit-identical; parity enforced in tests). Returns an int64 ndarray,
    or None when the native lib is unavailable."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    out = np.empty(n, dtype=np.int64)
    lib.tsb_permutation(
        seed & 0xFFFFFFFF, n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    )
    return out


def sampler_indices(length, num_replicas, rank, seed, epoch, shuffle, drop_last):
    """Native DistributedSampler epoch shard; None if lib unavailable."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    if drop_last and length % num_replicas != 0:
        num = length // num_replicas
    else:
        num = -(-length // num_replicas)
    out = np.empty(max(num, 1), dtype=np.int64)
    written = lib.tsb_sampler_indices(
        length, num_replicas, rank, seed & 0xFFFFFFFF, epoch,
        1 if shuffle else 0, 1 if drop_last else 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if written < 0:
        raise ValueError("invalid sampler arguments")
    return out[:written]


# -- staging ring ---------------------------------------------------------


class StagingRing:
    """Reusable aligned staging slots (pinned-memory equivalent). Producer
    threads acquire/commit; the consumer consumes/releases; buffers are
    zero-copy viewable as numpy arrays."""

    def __init__(self, n_slots: int, slot_bytes: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._ring = lib.tsb_ring_create(n_slots, slot_bytes)
        if not self._ring:
            raise MemoryError("ring allocation failed")
        self.slot_bytes = slot_bytes

    def acquire(self):
        buf = ctypes.c_void_p()
        slot = self._lib.tsb_ring_acquire(self._ring, ctypes.byref(buf))
        return slot, buf.value

    def commit(self, slot: int, size: int):
        self._lib.tsb_ring_commit(self._ring, slot, size)

    def consume(self):
        buf = ctypes.c_void_p()
        size = ctypes.c_int64()
        slot = self._lib.tsb_ring_consume(
            self._ring, ctypes.byref(buf), ctypes.byref(size)
        )
        return slot, buf.value, size.value

    def release(self, slot: int):
        self._lib.tsb_ring_release(self._ring, slot)

    def view(self, addr: int, nbytes: int):
        """numpy uint8 view of a slot buffer (no copy)."""
        import numpy as np

        return np.ctypeslib.as_array(
            (ctypes.c_uint8 * nbytes).from_address(addr)
        )

    def close(self):
        if self._ring:
            self._lib.tsb_ring_destroy(self._ring)
            self._ring = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# -- TCP store ------------------------------------------------------------


class TCPStoreServer:
    """Rank-0 rendezvous store server (torch TCPStore equivalent)."""

    def __init__(self, port: int = 0):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        out_port = ctypes.c_uint16()
        self._handle = lib.tsb_store_server_start(port, ctypes.byref(out_port))
        if not self._handle:
            raise OSError(f"could not bind store server on port {port}")
        self.port = out_port.value

    def stop(self):
        if self._handle:
            self._lib.tsb_store_server_stop(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class TCPStoreClient:
    """Client for :class:`TCPStoreServer`: set/get(blocking)/add, plus the
    barrier torch builds from counters."""

    def __init__(self, host: str, port: int):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._fd = lib.tsb_store_connect(host.encode(), port)
        if self._fd < 0:
            raise ConnectionError(f"could not connect to {host}:{port}")

    def set(self, key: str, value: bytes):
        if self._lib.tsb_store_set(
            self._fd, key.encode(),
            (ctypes.c_uint8 * len(value)).from_buffer_copy(value) if value
            else None,
            len(value),
        ) != 0:
            raise ConnectionError("set failed")

    def get(self, key: str, max_bytes: int = 1 << 20) -> bytes:
        buf = (ctypes.c_uint8 * max_bytes)()
        n = self._lib.tsb_store_get(self._fd, key.encode(), buf, max_bytes)
        if n < 0:
            raise ConnectionError("get failed")
        if n > max_bytes:
            raise ValueError(
                f"value for {key!r} is {n} bytes, larger than max_bytes="
                f"{max_bytes}; pass a bigger max_bytes"
            )
        return bytes(buf[:n])

    def add(self, key: str, delta: int) -> int:
        result = self._lib.tsb_store_add(self._fd, key.encode(), delta)
        if result == -(2**63):
            raise ConnectionError("add failed")
        return result

    def barrier(self, name: str, world: int):
        """All ``world`` participants block until everyone arrived — the
        store-barrier used by env:// rendezvous world assembly."""
        arrived = self.add(f"__barrier__{name}", 1)
        if arrived > world:
            raise RuntimeError(f"barrier {name!r} oversubscribed: {arrived}>{world}")
        if arrived == world:
            self.set(f"__barrier_done__{name}", b"1")
        else:
            self.get(f"__barrier_done__{name}")  # blocks until released

    def close(self):
        if self._fd >= 0:
            self._lib.tsb_store_close(self._fd)
            self._fd = -1

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
