"""Process & device runtime: the TPU-native equivalent of the reference's
L0/L1 layers (launcher env contract + rank→device binding + rendezvous;
reference ``README.md:11-36, 94-103``)."""

from tpu_syncbn.runtime.distributed import (
    initialize,
    is_initialized,
    shutdown,
    process_index,
    process_count,
    local_device_count,
    global_device_count,
    is_master,
    master_print,
    get_logger,
    data_parallel_mesh,
    make_mesh,
    barrier,
    DistributedConfig,
)

__all__ = [
    "initialize",
    "is_initialized",
    "shutdown",
    "process_index",
    "process_count",
    "local_device_count",
    "global_device_count",
    "is_master",
    "master_print",
    "get_logger",
    "data_parallel_mesh",
    "make_mesh",
    "barrier",
    "DistributedConfig",
]
