"""Process & device runtime: the TPU-native equivalent of the reference's
L0/L1 layers (launcher env contract + rank→device binding + rendezvous;
reference ``README.md:11-36, 94-103``)."""

from tpu_syncbn.runtime.distributed import (
    initialize,
    is_initialized,
    shutdown,
    process_index,
    process_count,
    local_device_count,
    global_device_count,
    is_master,
    master_print,
    get_logger,
    data_parallel_mesh,
    make_mesh,
    barrier,
    DistributedConfig,
)
from tpu_syncbn.runtime.resilience import (
    PreemptionGuard,
    ResilientLoop,
    StallError,
    Watchdog,
    backoff_delays,
    retry_with_backoff,
    stall_guard,
)

__all__ = [
    "PreemptionGuard",
    "ResilientLoop",
    "StallError",
    "Watchdog",
    "backoff_delays",
    "retry_with_backoff",
    "stall_guard",
    "initialize",
    "is_initialized",
    "shutdown",
    "process_index",
    "process_count",
    "local_device_count",
    "global_device_count",
    "is_master",
    "master_print",
    "get_logger",
    "data_parallel_mesh",
    "make_mesh",
    "barrier",
    "DistributedConfig",
]
