"""Single-program launcher — replaces ``python -m torch.distributed.launch
--nproc_per_node=N distributed_train.py`` (reference ``README.md:94-103``).

The reference launcher spawns one OS process per GPU and wires the env
contract (``MASTER_ADDR/PORT``, ``RANK``, ``WORLD_SIZE``, ``LOCAL_RANK``;
``[torch] distributed/run.py:211-232``). On TPU there is nothing to spawn:
one Python process per *host* drives all local chips, and chip-level
parallelism is the mesh. So the launcher's job shrinks to:

* initialize the distributed runtime (slice metadata / explicit flags);
* optionally simulate an N-chip mesh on CPU
  (``--simulate-chips``, via ``--xla_force_host_platform_device_count``)
  so the same script runs anywhere — the TPU analogue of debugging the
  recipe on the gloo backend;
* run the user's training script with ``__name__ == "__main__"`` intact.

Usage::

    python -m tpu_syncbn.launch [--simulate-chips 8] \
        [--coordinator host:port --num-processes H --process-id I] \
        your_train.py -- --your-script-args

No ``--local_rank`` is injected (reference step 1, ``README.md:11-19``):
scripts read identity from ``tpu_syncbn.runtime.process_index()``.
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpu_syncbn.launch",
        description="TPU-native launcher (replaces torch.distributed.launch)",
    )
    p.add_argument(
        "--simulate-chips",
        type=int,
        default=None,
        metavar="N",
        help="simulate an N-chip mesh on CPU host devices (testing without "
        "TPU hardware; sets --xla_force_host_platform_device_count)",
    )
    p.add_argument(
        "--nproc-per-node",
        type=int,
        default=None,
        metavar="N",
        help="compatibility alias for torch.distributed.launch's flag "
        "(reference README.md:96): there are no per-chip processes on TPU, "
        "so this asserts N == local chip count on hardware, or behaves "
        "like --simulate-chips N on CPU",
    )
    p.add_argument(
        "--coordinator",
        default=None,
        metavar="HOST:PORT",
        help="multi-host coordinator address (MASTER_ADDR:MASTER_PORT "
        "analogue; on a Cloud TPU slice leave unset — autodetected)",
    )
    p.add_argument("--num-processes", type=int, default=None,
                   help="number of host processes (WORLD_SIZE analogue)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this host's index (RANK analogue)")
    p.add_argument("script", help="training script to run")
    p.add_argument("script_args", nargs=argparse.REMAINDER,
                   help="arguments passed through to the script")
    return p


def main(argv: list[str] | None = None) -> None:
    args = build_parser().parse_args(argv)

    if args.nproc_per_node is not None and args.simulate_chips is None:
        # Probing the backend here would initialize it before the simulate
        # flags can take effect, so: CPU-only environments (no accelerator
        # platform requested) treat the flag as --simulate-chips; otherwise
        # the count is validated after runtime.initialize() below.
        # Only an EXPLICIT cpu request maps to simulation; unset means
        # autodetect (likely real TPU) and falls through to the
        # post-initialize chip-count validation.
        platforms = os.environ.get("JAX_PLATFORMS", "")
        if platforms.split(",")[0] == "cpu":
            args.simulate_chips = args.nproc_per_node

    if args.simulate_chips is not None:
        if args.simulate_chips < 1:
            raise SystemExit("--simulate-chips must be >= 1")
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.simulate_chips}"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        # jax may already be imported (e.g. launcher under pytest): the
        # env alone is too late then — mirror it into the live config.
        if "jax" in sys.modules:
            import jax

            jax.config.update("jax_platforms", "cpu")

    # export the env contract for DistributedConfig.from_env()
    if args.coordinator is not None:
        os.environ["TPU_SYNCBN_COORDINATOR"] = args.coordinator
    if args.num_processes is not None:
        os.environ["TPU_SYNCBN_NUM_PROCESSES"] = str(args.num_processes)
    if args.process_id is not None:
        os.environ["TPU_SYNCBN_PROCESS_ID"] = str(args.process_id)

    # Environments that pre-register an accelerator plugin at interpreter
    # start (sitecustomize) override JAX_PLATFORMS through jax.config; a
    # user-provided env value must win, so mirror it into the live config.
    if os.environ.get("JAX_PLATFORMS") and "jax" in sys.modules:
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from tpu_syncbn import runtime

    runtime.initialize()

    if args.nproc_per_node is not None and args.simulate_chips is None:
        import jax

        if jax.local_device_count() != args.nproc_per_node:
            raise SystemExit(
                f"--nproc-per-node={args.nproc_per_node} but this host has "
                f"{jax.local_device_count()} chips; on TPU the mesh spans "
                "all local chips automatically — drop the flag or match it"
            )

    script_args = args.script_args
    if script_args and script_args[0] == "--":
        script_args = script_args[1:]
    sys.argv = [args.script] + script_args
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
