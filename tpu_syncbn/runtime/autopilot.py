"""Closed-loop autopilot: the observability plane turns its own knobs.

PRs 11–14 made degradation *visible* — numerics drift (EF residual
ratio, clip saturation, BN mean skew), memory watermarks vs the pinned
contract, recompile storms, explained step time — but every monitor was
read-only: a human translated alerts into knob turns. The
:class:`Autopilot` closes that loop in the dynamic-quantization stance
of EQuARX (PAPERS.md, arXiv:2506.17615) and with the GSPMD line's
contract-costed-candidate discipline (arXiv:2004.13336): it consumes
the live signals the stack already publishes and actuates, **only at
fused-chunk boundaries and only within pre-audited bounds**, the knobs
the stack already exposes:

* **compression precision** — escalate int8 → bf16 → fp32 when the
  ``numerics_rules()`` SLOs burn (quantization drowning the signal),
  de-escalate one rung at a time after a sustained-healthy hysteresis
  window (:meth:`DataParallel.set_compress` — the EF residual rides
  opt_state with a *fixed* pytree structure across every rung, and each
  rung's programs are parked/recalled, never recompiled);
* **scan chunk length K** — raise it while the windowed attribution
  says host-gap/dispatch overhead dominates and ``mem.headroom_frac``
  allows; lower it when ``mem_pressure`` fires (the loop's per-chunk
  watchdog deadline follows the live K);
* **program-cache byte budgets** — shrink under memory pressure,
  regrow after the healthy window
  (:meth:`~tpu_syncbn.parallel.scan_driver.ProgramCache.set_max_bytes`);
* **pipeline microbatch count M** — drive M toward the schedule's
  predicted bubble optimum: raise it when the tick tables say a larger
  M buys a materially smaller bubble *and* the measured
  ``pipeline.bubble_frac`` gauge confirms there is bubble to reclaim;
  lower it under memory pressure (GPipe's in-flight activation stash
  grows with M — :meth:`Schedule.max_in_flight`). Actuates
  :meth:`~tpu_syncbn.parallel.pipeline.PipelineTrainer.set_microbatches`
  — prior Ms stay warm in the program cache, so moving back compiles
  nothing;
* **layout (planner-backed candidate-set mode)** — the controller
  holds a ranked plan list from
  :func:`tpu_syncbn.parallel.planner.plan` and *escalates* to the next
  planned layout when the measured mean step time violates the current
  plan's prediction by more than ``plan_tolerance``x. Layout moves are
  the one knob whose actuation fires the ``plan_change`` incident
  trigger (not ``autopilot``): a layout swap is a topology event worth
  its own bundle. Escalate-only by design — the planner re-ranks
  offline; the controller never walks back on its own.

Safety is the existing machinery, by construction:

* every selectable (compress-mode, K) variant is golden-pinned up
  front (``python -m tpu_syncbn.audit`` — the ``autopilot.*`` program
  contracts), so the controller can only move between
  contract-verified programs, and the recompile-storm detector proves
  mode flapping compiles nothing new;
* every decision — actuations, but also **clamped** attempts (the
  policy wanted to leave the candidate set) and **suppressed** ones
  (cooldown, divergence recovery in flight) — lands in the flight
  recorder's ``autopilot`` ring with the triggering signal and window
  quoted, and every actuation additionally fires the ``autopilot``
  incident-bundle trigger;
* the divergence guard + ``restore_last_good`` bound the blast radius
  of a bad policy step: :meth:`on_chunk` suppresses all actuation
  while the loop is recovering, and both rollback and mode switches
  zero the EF residual so stale wire-format error never replays.

Telemetry (all under the ``autopilot.`` family —
docs/OBSERVABILITY.md "Autopilot"): ``autopilot.actuations`` /
``autopilot.suppressed`` / ``autopilot.clamped`` counters, per-knob
state gauges ``autopilot.compress_rung`` / ``autopilot.scan_k`` /
``autopilot.cache_max_bytes`` (plus ``autopilot.microbatch_m`` /
``autopilot.plan_rank`` when those knobs are configured), and the
``autopilot.decision_s`` histogram (policy-evaluation cost per chunk
boundary).

Clocks are injectable (``now=``) and the SLO tracker is evaluated with
the same timestamp, so the whole state machine is deterministic under
test.
"""

from __future__ import annotations

import itertools
import time
from typing import Callable, Sequence

from tpu_syncbn.obs import flightrec, slo, telemetry, tracing

#: The compression ladder, most- to least-compressed. ``escalate``
#: moves right (toward the exact fp32 wire — the tentpole's
#: "int8 → bf16 → fp32"), ``deescalate`` moves left. Construct the
#: trainer at the leftmost rung you include so the EF residual exists
#: on every rung (opt_state structure is fixed at construction).
COMPRESS_LADDER = ("int8", "bf16", "none")

#: Default SLO families the autopilot watches. Serving-side families
#: exist (:func:`tpu_syncbn.obs.slo.standard_rules`) but none of the
#: training knobs answers to them.
DEFAULT_RULE_FAMILIES = ("numerics", "mem", "compile")

_COMPRESS_KNOB = "compress"
_K_KNOB = "scan_k"
_CACHE_KNOB = "cache_bytes"
_M_KNOB = "microbatch_m"
_LAYOUT_KNOB = "layout"
_KNOBS = (_COMPRESS_KNOB, _K_KNOB, _CACHE_KNOB, _M_KNOB, _LAYOUT_KNOB)


def _dispatch_seconds(snap: dict) -> float:
    """Summed in-dispatch seconds in a windowed snapshot — the same
    histogram families the incident attribution report counts as
    device-bound step time."""
    from tpu_syncbn.obs import incident

    hists = snap.get("histograms", {})
    return sum(
        hists[name]["sum"] for name in incident._DISPATCH_HISTS
        if name in hists
    )


def chunked_batches(batches, autopilot: "Autopilot"):
    """Adapt a per-STEP batch stream into K-stacked chunks whose K is
    the autopilot's live ``scan_k``, re-read at every chunk boundary —
    the data-side half of the K actuator (the trainer side needs
    nothing: ``train_steps_batches`` keys its scan cache by K, so every
    candidate's program is retained once compiled). The tail chunk is
    emitted at whatever length remains."""
    from tpu_syncbn.parallel import scan_driver

    it = iter(batches)
    while True:
        k = max(1, int(autopilot.scan_k))
        chunk = list(itertools.islice(it, k))
        if not chunk:
            return
        yield scan_driver.stack_batches(chunk)


class Autopilot:
    """The policy engine. One instance per training process; drive
    :meth:`on_chunk` at every fused-chunk boundary
    (``ResilientLoop(autopilot=...)`` does).

    ``trainer`` needs the :class:`~tpu_syncbn.parallel.trainer.DataParallel`
    knob surface (``compress``, ``set_compress``, ``program_caches``);
    pass ``None`` to run the compression knob open-loop (decisions are
    still recorded — a shadow-mode dry run). ``aggregator`` is the
    :class:`~tpu_syncbn.obs.timeseries.WindowedAggregator` the signals
    live in; ``rules`` defaults to
    ``slo.standard_rules(DEFAULT_RULE_FAMILIES)``.

    Knob bounds — the pre-audited candidate sets:

    * ``modes`` — orderable subset of :data:`COMPRESS_LADDER`
      (ladder order enforced); a burn at the top rung is *clamped*,
      counted, never an error;
    * ``k_candidates`` — ascending scan-K set; empty disables the K
      knob. ``set_scan_k`` is the actuation callback (the loop wires
      its chunk source through it);
    * ``cache_bytes_bounds`` — ``(floor, ceiling)`` for every cache in
      ``trainer.program_caches`` (plus ``extra_caches``); ``None``
      disables the knob;
    * ``m_candidates`` — ascending microbatch-count set for the
      pipeline M actuator (needs ``pipe_schedule`` + ``pipe_stages``
      so every candidate's bubble is derivable up front;
      ``set_microbatch`` is the actuation callback, normally
      ``PipelineTrainer.set_microbatches``); empty disables the knob;
    * ``plan_candidates`` — rank-ordered ``(name, predicted_step_s)``
      pairs (or planner ``PlannedCandidate``s — pass
      ``RankedPlans.top(k)`` directly) for the layout knob;
      ``set_layout`` receives the next plan's name on escalation;
      fewer than two candidates disables the knob.

    Policy timing: ``window_s`` is the evaluation window (signals are
    read over it; at most one actuation per knob per window —
    escalation latency is therefore bounded by one window), and
    ``healthy_for_s`` the de-escalation/regrow hysteresis (that long
    with no burn on the relevant family, measured from the *last* burn
    or actuation, whichever is later — a controller that just moved
    must re-observe before moving back, which is what prevents
    flapping)."""

    def __init__(
        self,
        trainer=None,
        *,
        aggregator,
        rules: Sequence | None = None,
        modes: Sequence[str] | None = None,
        k_candidates: Sequence[int] = (),
        set_scan_k: Callable[[int], None] | None = None,
        initial_k: int | None = None,
        cache_bytes_bounds: tuple[int, int] | None = None,
        extra_caches: Sequence = (),
        m_candidates: Sequence[int] = (),
        set_microbatch: Callable[[int], None] | None = None,
        initial_m: int | None = None,
        pipe_schedule: str | None = None,
        pipe_stages: int | None = None,
        bubble_margin: float = 0.02,
        plan_candidates: Sequence = (),
        set_layout: Callable[[str], None] | None = None,
        plan_tolerance: float = 1.5,
        window_s: float = 60.0,
        healthy_for_s: float = 300.0,
        host_gap_threshold: float = 0.3,
        headroom_min: float = 0.25,
        now=time.monotonic,
    ):
        if modes is None:
            modes = COMPRESS_LADDER if trainer is None else tuple(
                m for m in COMPRESS_LADDER
                if COMPRESS_LADDER.index(m)
                >= COMPRESS_LADDER.index(trainer.compress)
            )
        modes = tuple(modes)
        unknown = [m for m in modes if m not in COMPRESS_LADDER]
        if unknown:
            raise ValueError(
                f"modes {unknown} not in the audited ladder "
                f"{COMPRESS_LADDER}"
            )
        if list(modes) != sorted(modes, key=COMPRESS_LADDER.index):
            raise ValueError(
                f"modes must follow ladder order {COMPRESS_LADDER}, "
                f"got {modes}"
            )
        if not modes:
            raise ValueError("modes must name at least one rung")
        if trainer is not None and trainer.compress not in modes:
            raise ValueError(
                f"trainer is at {trainer.compress!r}, outside the "
                f"candidate set {modes}"
            )
        ks = tuple(int(k) for k in k_candidates)
        if list(ks) != sorted(set(ks)) or any(k < 1 for k in ks):
            raise ValueError(
                f"k_candidates must be ascending positive ints, got "
                f"{k_candidates}"
            )
        if cache_bytes_bounds is not None:
            floor, ceiling = cache_bytes_bounds
            if not 1 <= floor <= ceiling:
                raise ValueError(
                    f"cache_bytes_bounds needs 1 <= floor <= ceiling, "
                    f"got {cache_bytes_bounds}"
                )
        ms = tuple(int(m) for m in m_candidates)
        if list(ms) != sorted(set(ms)) or any(m < 1 for m in ms):
            raise ValueError(
                f"m_candidates must be ascending positive ints, got "
                f"{m_candidates}"
            )
        if ms and (pipe_schedule is None or pipe_stages is None):
            raise ValueError(
                "the microbatch knob needs pipe_schedule and "
                "pipe_stages (the predicted-bubble side of the policy "
                "comes from the static tick tables)"
            )
        if ms and pipe_stages is not None:
            from tpu_syncbn.parallel import pipeline_schedule

            for m in ms:
                # every candidate's schedule must be derivable up front
                # (candidate-set discipline: no first-actuation surprise)
                pipeline_schedule.get_schedule(
                    pipe_schedule, m, int(pipe_stages)
                )
        plans = []
        for cand in plan_candidates:
            if hasattr(cand, "candidate"):  # a planner PlannedCandidate
                plans.append((cand.name, float(cand.predicted_step_s)))
            else:
                name, predicted = cand
                plans.append((str(name), float(predicted)))
        if plans and len({n for n, _ in plans}) != len(plans):
            raise ValueError(
                f"plan_candidates repeat a layout name: "
                f"{[n for n, _ in plans]}"
            )
        if plan_tolerance < 1.0:
            raise ValueError(
                f"plan_tolerance must be >= 1.0 (a plan is violated "
                f"only when measured exceeds predicted), got "
                f"{plan_tolerance}"
            )
        if window_s <= 0 or healthy_for_s <= 0:
            raise ValueError(
                "window_s and healthy_for_s must be > 0, got "
                f"{window_s}/{healthy_for_s}"
            )
        self.trainer = trainer
        self.aggregator = aggregator
        self.tracker = slo.SLOTracker(
            aggregator,
            list(rules) if rules is not None
            else slo.standard_rules(DEFAULT_RULE_FAMILIES),
        )
        self.modes = modes
        self.k_candidates = ks
        self._set_scan_k = set_scan_k
        self.cache_bytes_bounds = cache_bytes_bounds
        self.extra_caches = tuple(extra_caches)
        self.m_candidates = ms
        self._set_microbatch = set_microbatch
        self.pipe_schedule = pipe_schedule
        self.pipe_stages = int(pipe_stages) if pipe_stages is not None \
            else None
        self.bubble_margin = float(bubble_margin)
        self.plan_candidates = tuple(plans)
        self._set_layout = set_layout
        self.plan_tolerance = float(plan_tolerance)
        self.plan_rank = 0
        self.window_s = float(window_s)
        self.healthy_for_s = float(healthy_for_s)
        self.host_gap_threshold = float(host_gap_threshold)
        self.headroom_min = float(headroom_min)
        self._now = now
        self.counters = telemetry.CounterGroup(prefix="autopilot")
        # knob state
        self.compress_rung = (
            modes.index(trainer.compress) if trainer is not None else 0
        )
        if initial_k is None:
            initial_k = ks[0] if ks else 1
        if ks and initial_k not in ks:
            raise ValueError(
                f"initial_k {initial_k} not in k_candidates {ks}"
            )
        self.scan_k = int(initial_k)
        if initial_m is None:
            initial_m = ms[0] if ms else None
        if ms and initial_m not in ms:
            raise ValueError(
                f"initial_m {initial_m} not in m_candidates {ms}"
            )
        self.microbatch_m = int(initial_m) if initial_m is not None \
            else None
        # per-knob last-actuation clocks (None = never): hysteresis
        # anchors — only real knob turns move them
        self._last_actuation: dict[str, float | None] = {
            knob: None for knob in _KNOBS
        }
        # per-knob last-decision clocks: the cooldown — clamps count
        # too, so a sustained burn at a bound writes one ring entry per
        # window, not one per chunk
        self._last_decision_t: dict[str, float | None] = {
            knob: None for knob in _KNOBS
        }
        # last time the knob's driving family burned (None = never seen
        # burning — de-escalation then keys off the first chunk's clock)
        self._last_numerics_burn: float | None = None
        self._last_mem_burn: float | None = None
        self._first_chunk_t: float | None = None
        self.last_decision: dict | None = None
        self.chunks = 0
        self._export_gauges()

    # -- helpers -----------------------------------------------------------

    def _caches(self) -> tuple:
        trainer_caches = (
            tuple(self.trainer.program_caches)
            if self.trainer is not None
            and hasattr(self.trainer, "program_caches") else ()
        )
        return trainer_caches + self.extra_caches

    def _cache_budget(self) -> int | None:
        """Current per-cache budget: the max over live budgets (they
        move in lockstep), or the ceiling when none is set yet."""
        if self.cache_bytes_bounds is None:
            return None
        budgets = [
            c.max_bytes for c in self._caches() if c.max_bytes is not None
        ]
        return max(budgets) if budgets else self.cache_bytes_bounds[1]

    def _healthy_since(self, knob: str, last_burn: float | None,
                       now: float) -> bool:
        """Sustained-healthy hysteresis: ``healthy_for_s`` elapsed since
        the later of (last burn on the driving family, this knob's last
        actuation, the first observed chunk)."""
        anchors = [
            t for t in (last_burn, self._last_actuation[knob],
                        self._first_chunk_t)
            if t is not None
        ]
        if not anchors:
            return False
        return now - max(anchors) >= self.healthy_for_s

    def _in_cooldown(self, knob: str, now: float) -> bool:
        last = self._last_decision_t[knob]
        return last is not None and now - last < self.window_s

    def _record(self, decision: dict, now: float) -> dict:
        """Every decision — actuation, clamp, or suppression — lands in
        the ring; actuations also fire the incident trigger (the
        recorder's cooldown bounds bundle frequency, the ring does not
        drop anything). Returns the enriched decision (t_mono, chunk)
        — what callers hand back from :meth:`on_chunk`."""
        decision = dict(decision, t_mono=round(now, 6),
                        chunk=self.chunks)
        self.last_decision = decision
        flightrec.record_autopilot(**decision)
        tracing.instant("autopilot", **{
            k: v for k, v in decision.items()
            if isinstance(v, (str, int, float, bool))
        })
        action = decision["action"]
        knob = decision["knob"]
        if action == "clamp":
            self.counters.bump("clamped")
            self._last_decision_t[knob] = now
        elif action == "suppress":
            self.counters.bump("suppressed")
        else:
            self.counters.bump("actuations")
            self._last_actuation[knob] = now
            self._last_decision_t[knob] = now
            # a layout swap is a topology event: it gets its own
            # incident kind so post-mortems can diff plan moves apart
            # from routine knob turns
            kind = "plan_change" if knob == _LAYOUT_KNOB else "autopilot"
            flightrec.trigger(kind, decision)
        return decision

    def _export_gauges(self) -> None:
        telemetry.set_gauge("autopilot.compress_rung", self.compress_rung)
        telemetry.set_gauge("autopilot.scan_k", self.scan_k)
        budget = self._cache_budget()
        if budget is not None:
            telemetry.set_gauge("autopilot.cache_max_bytes", budget)
        if self.microbatch_m is not None:
            telemetry.set_gauge("autopilot.microbatch_m",
                                self.microbatch_m)
        if self.plan_candidates:
            telemetry.set_gauge("autopilot.plan_rank", self.plan_rank)

    @staticmethod
    def _quote(state: dict, rule: str) -> dict:
        """The triggering signal's evidence, quoted into the decision:
        rule name plus its per-window burn rates."""
        burns = state.get(rule, {}).get("burns", {})
        return {str(w): (round(b, 4) if b is not None else None)
                for w, b in burns.items()}

    # -- the policy step ---------------------------------------------------

    def on_chunk(self, *, step: int | None = None, k: int | None = None,
                 recovering: bool = False) -> list[dict]:
        """One policy evaluation at a fused-chunk boundary; returns the
        decisions made (possibly empty). ``recovering=True`` (a
        divergence rollback is being re-validated) suppresses all
        actuation — the guard owns the process until the probation
        window passes."""
        t0 = time.perf_counter()
        now = self._now()
        self.chunks += 1
        if self._first_chunk_t is None:
            self._first_chunk_t = now
        decisions: list[dict] = []
        if recovering:
            d = self._record({"knob": "all", "action": "suppress",
                              "signal": "divergence_recovery",
                              "step": step}, now)
            decisions.append(d)
            telemetry.observe("autopilot.decision_s",
                              time.perf_counter() - t0)
            return decisions
        state = self.tracker.evaluate(now=now)
        snap = self.aggregator.windowed_snapshot(self.window_s, now=now)
        numerics_firing = [
            r for r in state
            if r.startswith("numerics") and state[r]["firing"]
        ]
        mem_firing = state.get("mem_pressure", {}).get("firing", False)
        if numerics_firing:
            self._last_numerics_burn = now
        if mem_firing:
            self._last_mem_burn = now
        decisions += self._compress_policy(state, numerics_firing, now,
                                           step)
        decisions += self._k_policy(state, snap, mem_firing, now, step)
        decisions += self._cache_policy(state, mem_firing, now, step)
        decisions += self._m_policy(state, snap, mem_firing, now, step)
        decisions += self._layout_policy(snap, now, step)
        self._export_gauges()
        telemetry.observe("autopilot.decision_s",
                          time.perf_counter() - t0)
        return decisions

    # -- knob policies -----------------------------------------------------

    def _compress_policy(self, state, numerics_firing, now, step):
        if len(self.modes) < 2:
            return []
        if self._in_cooldown(_COMPRESS_KNOB, now):
            return []
        base = {"knob": _COMPRESS_KNOB, "step": step,
                "window_s": self.window_s}
        if numerics_firing:
            signal = numerics_firing[0]
            base.update(signal=signal,
                        burns=self._quote(state, signal))
            if self.compress_rung + 1 < len(self.modes):
                frm = self.modes[self.compress_rung]
                self.compress_rung += 1
                to = self.modes[self.compress_rung]
                if self.trainer is not None:
                    self.trainer.set_compress(to)
                d = dict(base, action="escalate", frm=frm, to=to)
            else:
                # burning at the least-compressed rung: nowhere to go
                d = dict(base, action="clamp",
                         frm=self.modes[self.compress_rung])
            return [self._record(d, now)]
        if (self.compress_rung > 0
                and self._healthy_since(_COMPRESS_KNOB,
                                        self._last_numerics_burn, now)):
            frm = self.modes[self.compress_rung]
            self.compress_rung -= 1
            to = self.modes[self.compress_rung]
            if self.trainer is not None:
                self.trainer.set_compress(to)
            d = dict(base, action="deescalate", frm=frm, to=to,
                     signal="numerics_healthy",
                     healthy_for_s=self.healthy_for_s)
            return [self._record(d, now)]
        return []

    def _k_policy(self, state, snap, mem_firing, now, step):
        if not self.k_candidates or len(self.k_candidates) < 2:
            return []
        if self._in_cooldown(_K_KNOB, now):
            return []
        base = {"knob": _K_KNOB, "step": step, "window_s": self.window_s}
        idx = self.k_candidates.index(self.scan_k)
        if mem_firing:
            base.update(signal="mem_pressure",
                        burns=self._quote(state, "mem_pressure"))
            if idx > 0:
                frm, self.scan_k = self.scan_k, self.k_candidates[idx - 1]
                if self._set_scan_k is not None:
                    self._set_scan_k(self.scan_k)
                d = dict(base, action="lower", frm=frm, to=self.scan_k)
            else:
                d = dict(base, action="clamp", frm=self.scan_k)
            return [self._record(d, now)]
        covered = snap.get("window", {}).get("covered_s", 0.0)
        if covered <= 0:
            return []
        host_gap = max(0.0, 1.0 - _dispatch_seconds(snap) / covered)
        headroom = snap.get("gauges", {}).get("mem.headroom_frac")
        if (host_gap > self.host_gap_threshold
                and headroom is not None
                and headroom > self.headroom_min
                and self._healthy_since(_K_KNOB, self._last_mem_burn,
                                        now)):
            base.update(signal="host_gap",
                        host_gap_frac=round(host_gap, 4),
                        headroom_frac=round(headroom, 4))
            if idx + 1 < len(self.k_candidates):
                frm, self.scan_k = self.scan_k, self.k_candidates[idx + 1]
                if self._set_scan_k is not None:
                    self._set_scan_k(self.scan_k)
                d = dict(base, action="raise", frm=frm, to=self.scan_k)
            else:
                d = dict(base, action="clamp", frm=self.scan_k)
            return [self._record(d, now)]
        return []

    def _cache_policy(self, state, mem_firing, now, step):
        if self.cache_bytes_bounds is None or not self._caches():
            return []
        if self._in_cooldown(_CACHE_KNOB, now):
            return []
        floor, ceiling = self.cache_bytes_bounds
        budget = self._cache_budget()
        base = {"knob": _CACHE_KNOB, "step": step,
                "window_s": self.window_s}
        if mem_firing:
            base.update(signal="mem_pressure",
                        burns=self._quote(state, "mem_pressure"))
            if budget > floor:
                new = max(floor, budget // 2)
                for c in self._caches():
                    c.set_max_bytes(new)
                d = dict(base, action="shrink", frm=budget, to=new)
            else:
                d = dict(base, action="clamp", frm=budget)
            return [self._record(d, now)]
        if (budget < ceiling
                and self._healthy_since(_CACHE_KNOB, self._last_mem_burn,
                                        now)):
            new = min(ceiling, budget * 2)
            for c in self._caches():
                c.set_max_bytes(new)
            d = dict(base, action="grow", frm=budget, to=new,
                     signal="mem_healthy",
                     healthy_for_s=self.healthy_for_s)
            return [self._record(d, now)]
        return []

    def _predicted_bubble(self, m: int) -> float:
        from tpu_syncbn.parallel import pipeline_schedule

        return pipeline_schedule.get_schedule(
            self.pipe_schedule, m, self.pipe_stages
        ).predicted_bubble_frac

    def _m_policy(self, state, snap, mem_firing, now, step):
        """Drive M toward the schedule's predicted bubble optimum:
        raise it when the NEXT candidate's tick table predicts at least
        ``bubble_margin`` less bubble than the measured
        ``pipeline.bubble_frac`` says we are paying; lower it when
        ``mem_pressure`` fires (GPipe's in-flight activation stash
        scales with M). Predicted numbers come from the same exact
        arithmetic the planner costs with — measured-vs-predicted gap
        is the actuation evidence, quoted into the decision."""
        if not self.m_candidates or len(self.m_candidates) < 2:
            return []
        if self._in_cooldown(_M_KNOB, now):
            return []
        base = {"knob": _M_KNOB, "step": step, "window_s": self.window_s}
        idx = self.m_candidates.index(self.microbatch_m)
        if mem_firing:
            base.update(signal="mem_pressure",
                        burns=self._quote(state, "mem_pressure"))
            if idx > 0:
                frm = self.microbatch_m
                self.microbatch_m = self.m_candidates[idx - 1]
                if self._set_microbatch is not None:
                    self._set_microbatch(self.microbatch_m)
                d = dict(base, action="lower", frm=frm,
                         to=self.microbatch_m)
            else:
                d = dict(base, action="clamp", frm=self.microbatch_m)
            return [self._record(d, now)]
        measured = snap.get("gauges", {}).get("pipeline.bubble_frac")
        if measured is None:
            return []
        if not self._healthy_since(_M_KNOB, self._last_mem_burn, now):
            return []
        cur = self._predicted_bubble(self.microbatch_m)
        if idx + 1 < len(self.m_candidates):
            nxt_m = self.m_candidates[idx + 1]
            nxt = self._predicted_bubble(nxt_m)
            # two conditions: the tick table promises a material win,
            # and the measured bubble confirms there is that much to
            # reclaim (a noisy low measurement must not drive M up)
            if (cur - nxt >= self.bubble_margin
                    and measured >= nxt + self.bubble_margin):
                frm = self.microbatch_m
                self.microbatch_m = nxt_m
                if self._set_microbatch is not None:
                    self._set_microbatch(nxt_m)
                d = dict(base, action="raise", frm=frm, to=nxt_m,
                         signal="bubble_gap",
                         bubble_measured=round(measured, 4),
                         bubble_predicted=round(cur, 4),
                         bubble_predicted_next=round(nxt, 4))
                return [self._record(d, now)]
            return []
        # top of the candidate set but still paying a bubble the
        # margin says matters: clamp, visibly
        if measured >= cur + self.bubble_margin:
            d = dict(base, action="clamp", frm=self.microbatch_m,
                     signal="bubble_gap",
                     bubble_measured=round(measured, 4),
                     bubble_predicted=round(cur, 4))
            return [self._record(d, now)]
        return []

    def _layout_policy(self, snap, now, step):
        """Planner-backed candidate-set mode: hold the ranked plan
        list, compare the measured mean step time against the current
        plan's prediction, escalate one rank when the prediction is
        violated by more than ``plan_tolerance``x. Escalate-only (the
        planner re-ranks offline; the controller never walks back), and
        the actuation fires the ``plan_change`` incident trigger."""
        if len(self.plan_candidates) < 2:
            return []
        if self._in_cooldown(_LAYOUT_KNOB, now):
            return []
        hists = snap.get("histograms", {})
        from tpu_syncbn.obs import incident

        count = sum(
            hists[name]["count"] for name in incident._DISPATCH_HISTS
            if name in hists
        )
        if count <= 0:
            return []
        measured = _dispatch_seconds(snap) / count
        name, predicted = self.plan_candidates[self.plan_rank]
        if measured <= predicted * self.plan_tolerance:
            return []
        base = {"knob": _LAYOUT_KNOB, "step": step,
                "window_s": self.window_s, "signal": "plan_violation",
                "measured_step_s": round(measured, 6),
                "predicted_step_s": round(predicted, 6),
                "plan_tolerance": self.plan_tolerance}
        if self.plan_rank + 1 < len(self.plan_candidates):
            self.plan_rank += 1
            to_name = self.plan_candidates[self.plan_rank][0]
            if self._set_layout is not None:
                self._set_layout(to_name)
            d = dict(base, action="escalate", frm=name, to=to_name,
                     plan_rank=self.plan_rank)
        else:
            d = dict(base, action="clamp", frm=name)
        return [self._record(d, now)]

    # -- introspection -----------------------------------------------------

    def state(self) -> dict:
        """JSON-ready controller state (what a /statusz section or a
        test asserts on)."""
        return {
            "compress": self.modes[self.compress_rung],
            "compress_rung": self.compress_rung,
            "modes": list(self.modes),
            "scan_k": self.scan_k,
            "k_candidates": list(self.k_candidates),
            "cache_max_bytes": self._cache_budget(),
            "microbatch_m": self.microbatch_m,
            "m_candidates": list(self.m_candidates),
            "plan": (self.plan_candidates[self.plan_rank][0]
                     if self.plan_candidates else None),
            "plan_rank": self.plan_rank,
            "plan_candidates": [n for n, _ in self.plan_candidates],
            "chunks": self.chunks,
            "actuations": self.counters.count("actuations"),
            "clamped": self.counters.count("clamped"),
            "suppressed": self.counters.count("suppressed"),
            "last_decision": self.last_decision,
        }
