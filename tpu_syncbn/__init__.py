"""tpu-syncbn: a TPU-native data-parallel training framework with
synchronized BatchNorm.

A ground-up JAX/XLA/Pallas rebuild of the capability surface of
``dougsouza/pytorch-sync-batchnorm-example`` (reference ``README.md:1-104``):
multi-replica data-parallel training in which per-channel BatchNorm statistics
are reduced across *all* replicas each step, so that small per-chip batches
(object detection, GANs — reference ``README.md:3``) normalize against the
true global batch.

The reference's six-step recipe maps onto this package as:

==============================================  ================================
Reference step (README.md line)                  tpu-syncbn equivalent
==============================================  ================================
``--local_rank`` arg parsing (11-19)            none needed: single program,
                                                ``runtime.process_index()``
``torch.cuda.set_device`` +                     ``runtime.initialize()`` —
``init_process_group('nccl','env://')``         slice-metadata discovery, mesh
(22-36)                                         over ICI/DCN
``convert_sync_batchnorm`` (40-45)              ``nn.convert_sync_batchnorm``
``DistributedDataParallel`` wrap (62-72)        ``parallel.DataParallel`` /
                                                ``parallel.make_train_step``
``DistributedSampler`` + ``DataLoader``         ``data.DistributedSampler`` +
(74-92)                                         ``data.DataLoader``
``torch.distributed.launch`` (94-103)           ``python -m tpu_syncbn.launch``
==============================================  ================================
"""

__version__ = "0.1.0"

from tpu_syncbn import runtime, parallel, ops, nn, models, data, utils, obs, serve  # noqa: F401
