"""``python -m tpu_syncbn.launch`` entry point (reference ``README.md:96``:
``python -m torch.distributed.launch``)."""

from tpu_syncbn.runtime.launcher import main

if __name__ == "__main__":
    main()
